"""ComputationGraph — the DAG network container.

TPU-native equivalent of reference nn/graph/ComputationGraph.java (2,280 LoC):
topological forward (doForward per vertex, GraphVertex.java:117), autodiff
backward replacing doBackward (:123), multi-input/multi-output with
MultiDataSet, fit (:809), computeGradientAndScore (:952), flattened-params
contract (:281-345).

Same TPU-first redesign as MultiLayerNetwork: the whole training step
(params, updater_state, model_state, batch) -> (params', ...) is ONE donated
jit-compiled XLA program; the DAG structure is unrolled at trace time (the
topological order is static), so XLA sees a flat fused computation regardless
of graph shape.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ... import obs
from ...datasets.dataset import DataSet, MultiDataSet
from ...datasets.iterators import next_processed
from ..conf.computation_graph_configuration import ComputationGraphConfiguration
from ..conf.layers.base import LayerConf
from ..conf.layers.recurrent import BaseRecurrentLayer
from ..updater import updaters as U

log = logging.getLogger(__name__)


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration,
                 remat_segments=False):
        """remat_segments=True: gradient-checkpoint the graph in segments
        bounded by element-wise (residual-add) vertices — the backward
        recomputes each segment's conv→BN→ReLU chain from the segment
        boundary instead of re-reading every intermediate activation from
        HBM. Structural bytes/step lever for bandwidth-bound CNNs
        (PERF.md r2 roofline: ResNet-50 is HBM-bound); trades ~1/3 more
        forward FLOPs for activation traffic. Numerics are identical
        (pinned by test). The reference has no equivalent (it stores all
        activations; workspace reuse is its only memory lever —
        WorkspaceMode in MultiLayerConfiguration.java)."""
        self.conf = conf
        self._remat = bool(remat_segments)
        g = conf.global_conf
        dt = str(g.get("data_type", "float32"))
        self.compute_dtype = {"bfloat16": jnp.bfloat16,
                              "float64": jnp.float64}.get(dt, jnp.float32)
        self.param_dtype = jnp.float64 if dt == "float64" else jnp.float32
        self._params = None          # dict name -> param dict (layer vertices)
        self._updater_state = None
        self._model_state = None     # dict name -> state dict
        self._rng = jax.random.PRNGKey(int(g.get("seed", 123)))
        self.listeners = []
        self._score = None
        self._last_batch_size = 0
        self._jit_step = None
        self._jit_forward = {}
        self._loop = None            # device-resident {iteration, rng}

    # ------------------------------------------------------------------
    def _layer_names(self):
        """Layer vertices in topological order (the flattened-params order —
        reference ComputationGraph.init:281-345 uses topological order too)."""
        return [n for n in self.conf.topological_order
                if self.conf.vertices[n].is_layer]

    def init(self, parameters=None, clone_parameters=False):
        if self._params is None:
            names = self._layer_names()
            keys = jax.random.split(self._rng, len(names) + 1)
            self._rng = keys[0]
            self._params = {}
            self._model_state = {}
            for i, n in enumerate(names):
                layer = self.conf.vertices[n].conf
                self._params[n] = layer.init_params(keys[i + 1], self.param_dtype)
                self._model_state[n] = layer.init_state()
            self._init_updater_state()
        if parameters is not None:
            self.set_params(parameters)
        return self

    def _init_updater_state(self):
        sd = self.conf.global_conf.get("updater_state_dtype")
        self._updater_state = {}
        for n in self._layer_names():
            layer = self.conf.vertices[n].conf
            init_fn, _ = U.get(layer.updater or "sgd")
            st = {k: init_fn(v) for k, v in self._params[n].items()}
            self._updater_state[n] = U.cast_updater_state(st, sd)

    def _ensure_init(self):
        if self._params is None:
            self.init()

    # ------------------------------------------------------------------
    # Forward — reference: per-vertex doForward in topological order
    # ------------------------------------------------------------------
    def _apply_graph(self, params, state, inputs, *, train, rng, fmasks=None,
                     stop_at=None, carries=None, allow_remat=False):
        """Pure forward over the DAG.

        inputs: dict input-name -> array. fmasks: dict input-name -> mask.
        carries: dict layer-name -> RNN carry (TBPTT / rnnTimeStep state).
        Returns (activations dict incl. inputs, new_state dict, masks dict,
        new_carries dict).
        """
        cdt = self.compute_dtype
        # remat only wraps the TRAINING-STEP forward (allow_remat is set
        # by _loss_fn alone — what the backward stores); inference AND
        # inspection (feed_forward/UI activation capture, any train flag)
        # keep the full per-vertex activation contract
        if (self._remat and allow_remat and train and stop_at is None
                and carries is None
                and not (fmasks and any(m is not None
                                        for m in fmasks.values()))):
            return self._apply_graph_remat(params, state, inputs,
                                           train=train, rng=rng)
        acts = {}
        masks = {}
        for name in self.conf.network_inputs:
            x = inputs[name]
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(cdt)
            acts[name] = x
            masks[name] = fmasks.get(name) if fmasks else None
        new_state = dict(state)
        new_carries = dict(carries) if carries is not None else None
        for vi, name in enumerate(self.conf.topological_order):
            spec = self.conf.vertices[name]
            in_acts = [acts[i] for i in spec.inputs]
            in_masks = [masks.get(i) for i in spec.inputs]
            lrng = jax.random.fold_in(rng, vi) if rng is not None else None
            out, st, c = self._forward_vertex(
                spec, params.get(name), in_acts, in_masks, train=train,
                lrng=lrng, state_entry=state.get(name),
                carry_entry=(carries or {}).get(name)
                if carries is not None else None)
            acts[name] = out
            if st is not None:
                new_state[name] = st
            if c is not None:
                new_carries[name] = c
            if spec.is_layer:
                masks[name] = (in_masks[0]
                               if _keeps_time_axis(spec.conf) else None)
            else:
                masks[name] = spec.conf.output_mask(in_masks)
            if stop_at is not None and name == stop_at:
                break
        return acts, new_state, masks, new_carries

    def _cast_params(self, p):
        cdt = self.compute_dtype
        return jax.tree.map(
            lambda a: a.astype(cdt)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)

    def _forward_vertex(self, spec, p, in_acts, in_masks, *, train, lrng,
                        state_entry=None, carry_entry=None):
        """One vertex's forward — the SINGLE dispatch (preprocessor, param
        cast, carry/state/stateless branches) shared by `_apply_graph` and
        the remat segment body, so the two forward paths cannot drift.
        Returns (out, new_state | None, new_carry | None)."""
        if spec.is_layer:
            layer = spec.conf
            x = in_acts[0]
            if spec.preprocessor is not None:
                x = spec.preprocessor.pre_process(x)
            p = self._cast_params(p)
            m = in_masks[0]
            if (isinstance(layer, BaseRecurrentLayer)
                    and carry_entry is not None):
                out, c = layer.forward_with_carry(
                    p, x, carry_entry, train=train, rng=lrng, mask=m)
                return out, None, c
            if layer.has_state():
                out, st = layer.forward_with_state(
                    p, x, state_entry, train=train, rng=lrng, mask=m)
                return out, st, None
            return (layer.forward(p, x, train=train, rng=lrng, mask=m),
                    None, None)
        return (spec.conf.forward(in_acts, masks=in_masks, train=train,
                                  rng=lrng), None, None)

    def _remat_plan(self):
        """Segment the topological order at element-wise (residual-add)
        vertex boundaries. Returns (segment-id per vertex, n_segments)."""
        if getattr(self, "_remat_plan_cache", None) is None:
            from ..conf.graph_vertices import ElementWiseVertex
            seg, s = {}, 0
            for name in self.conf.topological_order:
                seg[name] = s
                spec = self.conf.vertices[name]
                if (not spec.is_layer
                        and isinstance(spec.conf, ElementWiseVertex)):
                    s += 1
            self._remat_plan_cache = (seg, s + 1)
        return self._remat_plan_cache

    def _apply_graph_remat(self, params, state, inputs, *, train, rng):
        """`_apply_graph` with each residual segment under `jax.checkpoint`:
        only segment-boundary activations become autodiff residuals; the
        interior (conv outputs, BN normalized, ReLU) is recomputed during
        the backward. Only reached for mask-free, carry-free graphs (the
        CNN shape this lever targets)."""
        cdt = self.compute_dtype
        seg_of, n_seg = self._remat_plan()
        order = self.conf.topological_order
        segments = [[] for _ in range(n_seg)]
        for name in order:
            segments[seg_of[name]].append(name)
        # activations needed beyond their own segment stay live; output
        # heads' INPUTS too — _loss_fn recomputes each head on its
        # pre-head activation to attach the loss
        needed_later = set(self.conf.network_outputs)
        for out in self.conf.network_outputs:
            needed_later.update(self.conf.vertices[out].inputs)
        for name in order:
            for inp in self.conf.vertices[name].inputs:
                if seg_of.get(inp, -1) != seg_of[name]:
                    needed_later.add(inp)
        vi_of = {name: i for i, name in enumerate(order)}
        acts = {}
        for name in self.conf.network_inputs:
            x = inputs[name]
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(cdt)
            acts[name] = x
        new_state = dict(state)

        for si, seg_names in enumerate(segments):
            if not seg_names:
                continue
            ext_in = sorted({i for n in seg_names
                             for i in self.conf.vertices[n].inputs
                             if seg_of.get(i, -1) != si})
            layer_names = tuple(n for n in seg_names
                                if self.conf.vertices[n].is_layer)
            stateful = tuple(n for n in layer_names
                             if self.conf.vertices[n].conf.has_state())
            out_names = tuple(n for n in seg_names if n in needed_later)

            def seg_fn(p_sub, st_sub, in_list, _names=tuple(seg_names),
                       _ext=tuple(ext_in), _outs=out_names):
                local = dict(zip(_ext, in_list))
                st_new = {}
                for name in _names:
                    spec = self.conf.vertices[name]
                    in_acts = [local[i] for i in spec.inputs]
                    lrng = (jax.random.fold_in(rng, vi_of[name])
                            if rng is not None else None)
                    # same vertex dispatch as the default path — shared
                    # helper, so the two forwards cannot drift
                    out, st, _ = self._forward_vertex(
                        spec, p_sub.get(name), in_acts,
                        [None] * len(in_acts), train=train, lrng=lrng,
                        state_entry=st_sub.get(name))
                    if st is not None:
                        st_new[name] = st
                    local[name] = out
                return [local[o] for o in _outs], st_new

            # the final segment (head + loss inputs) gains nothing from
            # recompute — its residuals back the loss directly
            call = jax.checkpoint(seg_fn) if si < n_seg - 1 else seg_fn
            outs, st_new = call({n: params[n] for n in layer_names},
                                {n: state[n] for n in stateful},
                                [acts[i] for i in ext_in])
            acts.update(zip(out_names, outs))
            new_state.update(st_new)
        masks = {name: None for name in acts}
        return acts, new_state, masks, None

    def _canon_inputs(self, features):
        if isinstance(features, dict):
            return features
        if not isinstance(features, (list, tuple)):
            features = [features]
        if len(features) != len(self.conf.network_inputs):
            raise ValueError(
                f"Graph has {len(self.conf.network_inputs)} inputs "
                f"{self.conf.network_inputs}, got {len(features)} arrays")
        return dict(zip(self.conf.network_inputs, features))

    def _canon_masks(self, masks):
        if masks is None:
            return None
        if isinstance(masks, dict):
            return masks
        if not isinstance(masks, (list, tuple)):
            masks = [masks]
        return {n: m for n, m in zip(self.conf.network_inputs, masks)
                if m is not None}

    # ------------------------------------------------------------------
    # Loss over output vertices
    # ------------------------------------------------------------------
    def _loss_fn(self, params, state, features, labels, fmasks, lmasks, rng,
                 train, carries=None):
        """features: dict name->arr; labels: list aligned with network_outputs."""
        acts, new_state, masks, new_carries = self._apply_graph(
            params, state, features, train=train, rng=rng, fmasks=fmasks,
            carries=carries, allow_remat=True)
        total = 0.0
        order = {n: i for i, n in enumerate(self.conf.topological_order)}
        for oi, out_name in enumerate(self.conf.network_outputs):
            spec = self.conf.vertices[out_name]
            layer = spec.conf
            if not hasattr(layer, "compute_score_per_example"):
                continue  # non-loss output (pure inference head)
            # recompute the head on its pre-head input to attach the loss
            x = acts[spec.inputs[0]]
            if spec.preprocessor is not None:
                x = spec.preprocessor.pre_process(x)
            p = self._cast_params(params[out_name])
            lrng = (jax.random.fold_in(rng, order[out_name])
                    if rng is not None else None)
            lmask = None
            if lmasks:
                lmask = (lmasks[oi] if isinstance(lmasks, (list, tuple))
                         else lmasks.get(out_name))
            per_ex = layer.compute_score_per_example(
                p, x, labels[oi], train=train, rng=lrng, mask=lmask)
            if per_ex.dtype == jnp.bfloat16:
                per_ex = per_ex.astype(jnp.float32)
            total = total + jnp.mean(per_ex)
        reg = 0.0
        for n in self._layer_names():
            reg = reg + self.conf.vertices[n].conf.reg_score(params[n])
        return total + reg, (new_state, new_carries)

    # ------------------------------------------------------------------
    # Fused train step (same contract as MultiLayerNetwork.make_raw_step)
    # ------------------------------------------------------------------
    def make_grad_fn(self):
        """(params, state, batch) -> (grads, score, new_state, new_carries) —
        gradient half of the step (async-PS worker compute; see
        multilayer.make_grad_fn)."""
        def grad_fn(params, state, batch):
            (score, (new_state, new_carries)), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(
                    params, state, batch["features"], batch["labels"],
                    batch.get("fmask"), batch.get("lmask"), batch["rng"],
                    True, batch.get("carries"))
            return grads, score, new_state, new_carries
        return grad_fn

    def make_apply_fn(self):
        """(params, ustate, grads, iteration) -> (new_params, new_ustate) —
        updater half of the step (reference ComputationGraphUpdater)."""
        names = self._layer_names()

        def apply_updates(params, ustate, grads, iteration):
            minimize = self.conf.global_conf.get("minimize", True)
            new_params = dict(params)
            new_ustate = dict(ustate)
            for n in names:
                layer = self.conf.vertices[n].conf
                g_n = U.normalize_gradients(
                    grads[n], layer.gradient_normalization,
                    layer.gradient_normalization_threshold or 1.0)
                _, apply_fn = U.get(layer.updater or "sgd")
                hp = layer.updater_hp()
                p_new, s_new = {}, {}
                for k, p in params[n].items():
                    base_lr = layer.learning_rate or 0.1
                    if k in ("b", "beta") and layer.bias_learning_rate is not None:
                        base_lr = layer.bias_learning_rate
                    lr = U.schedule_lr(
                        base_lr, layer.lr_policy or "none", iteration,
                        decay_rate=layer.lr_policy_decay_rate or 0.0,
                        steps=layer.lr_policy_steps or 1.0,
                        power=layer.lr_policy_power or 1.0,
                        schedule_map=layer.lr_schedule,
                        max_iterations=layer.lr_policy_max_iterations)
                    upd, s_k = apply_fn(ustate[n][k], g_n[k], lr, hp)
                    p_new[k] = p - upd if minimize else p + upd
                    # keep the stored state dtype (bf16 when
                    # updater_state_dtype is set; math promotes to f32)
                    s_new[k] = jax.tree.map(
                        lambda a, old: a.astype(old.dtype), s_k, ustate[n][k])
                new_params[n] = p_new
                new_ustate[n] = s_new
            return new_params, new_ustate

        return apply_updates

    def make_raw_step(self, emit_health=False):
        """Same contract as MultiLayerNetwork.make_raw_step:
        emit_health=True appends the scalar health pytree to the return
        tuple and gates the whole update on the all-finite predicate
        (`jnp.where` — a poisoned batch is skipped on device); False
        compiles the identical program as before."""
        grad_fn = self.make_grad_fn()
        apply_updates = self.make_apply_fn()

        def step(params, ustate, state, batch):
            grads, score, new_state, new_carries = grad_fn(params, state,
                                                           batch)
            new_params, new_ustate = apply_updates(params, ustate, grads,
                                                   batch["iteration"])
            if emit_health:
                from ...common import health as H
                health = H.grad_health(grads, score)
                ok = health["all_finite"]
                new_params = H.gate_update(ok, new_params, params)
                new_ustate = H.gate_update(ok, new_ustate, ustate)
                new_state = H.gate_update(ok, new_state, state)
                if batch.get("carries") is not None:
                    new_carries = H.gate_update(ok, new_carries,
                                                batch["carries"])
                return (new_params, new_ustate, new_state, score,
                        new_carries, health)
            return new_params, new_ustate, new_state, score, new_carries

        return step

    def _make_step(self):
        emit_health = getattr(self, "_health_policy", None) is not None
        self._step_emits_health = emit_health
        raw = self.make_raw_step(emit_health)

        def step(params, ustate, state, loop, features, labels, fmask, lmask,
                 carries=None):
            # device-resident loop state (iteration counter + PRNG key):
            # advances inside the compiled step — no per-iteration host
            # scalar transfer or key-split dispatch (see multilayer.py)
            rng, next_rng = jax.random.split(loop["rng"])
            batch = {"features": features, "labels": labels, "fmask": fmask,
                     "lmask": lmask, "iteration": loop["iteration"],
                     "rng": rng, "carries": carries}
            p, u, s, score, car, *extras = raw(params, ustate, state, batch)
            # loop state advances on skipped steps too (see multilayer.py)
            new_loop = {"iteration": loop["iteration"] + 1.0, "rng": next_rng}
            return (p, u, s, score, car, new_loop) + tuple(extras)

        return jax.jit(step, donate_argnums=(0, 1, 2, 3))

    def training_health(self, policy=True, checkpoint_dir=None,
                        checkpoint_every=10, keep_checkpoints=3):
        """Arm the training-health watchdog (see
        MultiLayerNetwork.training_health — identical contract)."""
        from ...common import health as H
        H.install(self, policy, checkpoint_dir, checkpoint_every,
                  keep_checkpoints)
        return self

    def fused_steps(self, k=8):
        """Fuse K optimizer steps into one device dispatch (see
        MultiLayerNetwork.fused_steps — identical contract; multi-input
        feature dicts and multi-output label lists stack per leaf)."""
        from .. import fused as F
        return F.install(self, k)

    def _fused_k(self):
        k = getattr(self, "_fused_steps", 1)
        if (k <= 1
                or int(self.conf.global_conf.get("num_iterations", 1)) != 1):
            return 1
        return k

    def _loop_state(self):
        if self._loop is None:
            self._rng, k = jax.random.split(self._rng)
            self._loop = {
                "iteration": jnp.asarray(self.conf.iteration_count,
                                         jnp.float32),
                "rng": k,
            }
        return self._loop

    # ------------------------------------------------------------------
    # fit — reference ComputationGraph.fit:809
    # ------------------------------------------------------------------
    def fit(self, data, labels=None, num_epochs=1):
        self._ensure_init()
        if labels is not None:
            data = MultiDataSet(data, labels)
        if isinstance(data, DataSet):
            data = _dataset_to_mds(data)
        if isinstance(data, MultiDataSet):
            return self._fit_mds(data)
        # iterator of DataSet / MultiDataSet: prefetch + stage off the
        # training thread like the reference (ComputationGraph.fit wraps
        # in Async(Multi)DataSetIterator), with the bf16 feature wire for
        # bf16 models (bit-identical — the step casts features anyway)
        from ...datasets.iterators import (AsyncDataSetIterator,
                                           DataSetIterator,
                                           wrap_async_for_fit)
        wrapped_here = False
        if isinstance(data, DataSetIterator):
            # the wrapper stages DataSet AND MultiDataSet batches
            # (per-batch dispatch), so one class covers both protocols.
            # A caller-supplied plain iterator may be mid-stream: reset
            # BEFORE wrapping so the fresh wrapper prefetches from 0 and
            # the epoch-0 reset skip is trivially safe (ADVICE r5)
            wrapped_here = not isinstance(data, AsyncDataSetIterator)
            if wrapped_here:
                data.reset()
            data = wrap_async_for_fit(
                data, self.compute_dtype,
                queue_size=max(2, getattr(self, "_fused_steps", 1) + 1))
        for epoch in range(num_epochs):
            # a fresh async wrapper fit() itself created is already
            # prefetching; resetting it on epoch 0 would drain (and
            # stage) one full pass unseen. CALLER-supplied iterators may
            # be mid-stream and reset unconditionally (ADVICE r5)
            if hasattr(data, "reset") and (
                    epoch > 0 or not wrapped_here
                    or not getattr(data, "has_next", lambda: False)()):
                data.reset()
            it = iter(data) if not hasattr(data, "has_next") else None
            if it is not None:
                for ds in it:
                    self._fit_mds(_dataset_to_mds(ds)
                                  if isinstance(ds, DataSet) else ds)
            else:
                while data.has_next():
                    k = (self._fused_k()
                         if self.conf.backprop_type != "tbptt" else 1)
                    if k <= 1:
                        ds = next_processed(data)
                        self._fit_mds(_dataset_to_mds(ds)
                                      if isinstance(ds, DataSet) else ds)
                        continue
                    from .. import fused as F
                    group = []
                    g = F.group_size(self, k)
                    with obs.TRACER.span("train.stage", cat="train", k=g):
                        while len(group) < g and data.has_next():
                            ds = next_processed(data)
                            group.append(_dataset_to_mds(ds)
                                         if isinstance(ds, DataSet) else ds)
                    if len(group) == g and F.uniform_group(group):
                        self._fit_mds_fused(group)
                    else:
                        # ragged tail / mixed shapes: single-step stream
                        for mds in group:
                            self._fit_mds(mds)
            self.conf.epoch_count += 1
        return self

    def _canon_mds(self, mds):
        """One MultiDataSet -> the raw-step batch pieces (name-keyed
        feature dict, label list, mask trees) — the _fit_mds conversion,
        shared with the fused super-batch path."""
        features = {n: jnp.asarray(f)
                    for n, f in zip(self.conf.network_inputs, mds.features)}
        labels = [jnp.asarray(l) for l in mds.labels]
        fmasks = None
        if mds.features_masks:
            fmasks = {n: jnp.asarray(m) if m is not None else None
                      for n, m in zip(self.conf.network_inputs,
                                      mds.features_masks)}
        lmasks = None
        if mds.labels_masks:
            lmasks = [jnp.asarray(m) if m is not None else None
                      for m in mds.labels_masks]
        return features, labels, fmasks, lmasks

    def _fit_mds_fused(self, group):
        """ONE dispatch for len(group) staged MultiDataSets (see
        MultiLayerNetwork._fit_super_batch — same contract, tree-stacked
        multi-input/multi-output batch pieces)."""
        from .. import fused as F
        emit_health = getattr(self, "_health_policy", None) is not None
        g = len(group)
        parts = [self._canon_mds(mds) for mds in group]

        def build():
            raw = self.make_raw_step(emit_health)

            def prog(params, ustate, state, loop, batch_list):
                return F.scan_batches(raw, params, ustate, state, loop,
                                      batch_list)

            return jax.jit(prog, donate_argnums=(0, 1, 2, 3))

        step = F.fused_program(self, ("batch", g), build)
        batch_list = tuple(
            {"features": p[0], "labels": p[1], "fmask": p[2],
             "lmask": p[3]} for p in parts)
        self._last_batch_size = int(
            jax.tree.leaves(parts[0][0])[0].shape[0])
        with obs.TRACER.span("train.fused_group", cat="train", k=g):
            with obs.TRACER.span("train.dispatch", cat="train", k=g):
                (self._params, self._updater_state, self._model_state,
                 scores, _, self._loop, *extras) = step(
                     self._params, self._updater_state, self._model_state,
                     self._loop_state(), batch_list)
            from ...common import health as H
            with obs.TRACER.span("train.health", cat="train", k=g):
                rb = H.finish_fused(self, scores,
                                    extras[-1] if emit_health else None, g)
        if rb is not None:
            for mds in group[rb + 1:]:  # counters/rng restored; replay
                self._fit_mds(mds)
        return self

    def _fit_mds(self, mds: MultiDataSet):
        if self._jit_step is None:
            self._jit_step = self._make_step()
        features, labels, fmasks, lmasks = self._canon_mds(mds)
        self._last_batch_size = int(mds.features[0].shape[0])
        if self.conf.backprop_type == "tbptt":
            return self._fit_tbptt(features, labels, fmasks, lmasks)
        num_iterations = int(self.conf.global_conf.get("num_iterations", 1))
        for _ in range(num_iterations):
            with obs.TRACER.span("train.dispatch", cat="train"):
                (self._params, self._updater_state, self._model_state,
                 score, _, self._loop, *extras) = self._jit_step(
                     self._params, self._updater_state, self._model_state,
                     self._loop_state(), features, labels, fmasks, lmasks)
            action = "ok"
            if not getattr(self, "_step_emits_health", False):
                self._score = score
            else:
                from ...common import health as H
                with obs.TRACER.span("train.health", cat="train"):
                    action = H.finish_step(self, extras[-1], score)
                if action == "rollback":
                    break           # counters/rng restored; next batch
            self.conf.iteration_count += 1
            for l in self.listeners:
                l.iteration_done(self, self.conf.iteration_count - 1)
            if action == "ok" and getattr(self, "_step_emits_health", False):
                from ...common.health import fit_loop_checkpoint
                with obs.TRACER.span("train.checkpoint", cat="train"):
                    fit_loop_checkpoint(self)
        return self

    # ------------------------------------------------------------------
    # TBPTT + streaming RNN state — reference ComputationGraph TBPTT path
    # + rnnTimeStep
    # ------------------------------------------------------------------
    def _recurrent_names(self):
        return [n for n in self._layer_names()
                if isinstance(self.conf.vertices[n].conf, BaseRecurrentLayer)]

    def _init_carries(self, batch_size):
        # compute dtype, not param dtype — see MultiLayerNetwork
        # ._init_carries (cast-on-entry makes values identical; the
        # returned carry is compute dtype, which the fused scan requires)
        return {n: self.conf.vertices[n].conf.init_carry(batch_size,
                                                         self.compute_dtype)
                for n in self._recurrent_names()}

    def _fit_tbptt(self, features, labels, fmasks, lmasks):
        """Slice the time axis into tbptt_fwd_length segments, carrying RNN
        state (not gradients) across segments — reference ComputationGraph
        TBPTT (same semantics as MultiLayerNetwork.doTruncatedBPTT:1140)."""
        seq_names = [n for n, f in features.items() if f.ndim >= 3]
        T = int(features[seq_names[0]].shape[1])
        L = self.conf.tbptt_fwd_length
        B = int(next(iter(features.values())).shape[0])
        carries = self._init_carries(B)
        t0 = 0
        while t0 < T:
            k = self._fused_k()
            if k > 1:
                from .. import fused as F
                g = min(F.group_size(self, k), (T - t0) // L)
                if g > 1:
                    carries, t0, done = self._fit_tbptt_fused(
                        features, labels, fmasks, lmasks, carries, t0, g,
                        T, L)
                    if done:        # rollback: abandon this sequence
                        return self
                    continue
            def _seg(a):
                # only sequence-shaped arrays have a time axis to slice;
                # static inputs/labels/masks pass through whole
                if a is None or a.ndim < 2 or a.shape[1] < T:
                    return a
                return a[:, t0:t0 + L]

            f_seg = {n: (_seg(f) if f.ndim >= 3 else f)
                     for n, f in features.items()}
            l_seg = [(_seg(l) if l.ndim >= 3 else l) for l in labels]
            fm_seg = ({n: _seg(m) for n, m in fmasks.items()}
                      if fmasks else None)
            lm_seg = ([_seg(m) for m in lmasks] if lmasks else None)
            with obs.TRACER.span("train.dispatch", cat="train",
                                 tbptt=True):
                (self._params, self._updater_state, self._model_state,
                 score, carries, self._loop, *extras) = self._jit_step(
                     self._params, self._updater_state, self._model_state,
                     self._loop_state(), f_seg, l_seg, fm_seg, lm_seg,
                     carries)
            action = "ok"
            if not getattr(self, "_step_emits_health", False):
                self._score = score
            else:
                from ...common import health as H
                action = H.finish_step(self, extras[-1], score)
                if action == "rollback":
                    break       # abandon the rest of this sequence
            self.conf.iteration_count += 1
            for l in self.listeners:
                l.iteration_done(self, self.conf.iteration_count - 1)
            if action == "ok" and getattr(self, "_step_emits_health", False):
                from ...common.health import fit_loop_checkpoint
                with obs.TRACER.span("train.checkpoint", cat="train"):
                    fit_loop_checkpoint(self)
            t0 += L
        return self

    def _fit_tbptt_fused(self, features, labels, fmasks, lmasks, carries,
                         t0, g, T, L):
        """ONE dispatch for g full TBPTT segments (see
        MultiLayerNetwork._fit_tbptt_fused): the scan body dynamic-slices
        sequence-shaped arrays (static inputs/labels/masks pass through
        whole, as in the sequential loop) and threads the RNN carries
        through the scan carry. Returns (carries', next_t0, rolled_back)."""
        from .. import fused as F
        emit_health = getattr(self, "_health_policy", None) is not None

        def build():
            raw = self.make_raw_step(emit_health)

            def prog(params, ustate, state, loop, features, labels,
                     fmask, lmask, carries, t0s):
                def make_batch(s):
                    def sl(a, min_ndim):
                        # same slice conditions as the sequential loop's
                        # _seg (static at trace time): features/labels
                        # only when sequence-shaped (ndim >= 3), masks
                        # from ndim >= 2; arrays without a full time
                        # axis pass through whole
                        if (a is None or a.ndim < min_ndim
                                or a.ndim < 2 or a.shape[1] < T):
                            return a
                        return jax.lax.dynamic_slice_in_dim(a, s, L, axis=1)

                    return {"features": jax.tree.map(
                                lambda a: sl(a, 3), features),
                            "labels": jax.tree.map(
                                lambda a: sl(a, 3), labels),
                            "fmask": (jax.tree.map(
                                lambda a: sl(a, 2), fmask)
                                if fmask is not None else None),
                            "lmask": (jax.tree.map(
                                lambda a: sl(a, 2), lmask)
                                if lmask is not None else None)}

                return F.scan_steps(raw, params, ustate, state, loop,
                                    carries, t0s, make_batch)

            return jax.jit(prog, donate_argnums=(0, 1, 2, 3))

        key = ("tbptt", g, T, L,
               fmasks is not None, lmasks is not None)
        step = F.fused_program(self, key, build)
        t0s = jnp.arange(t0, t0 + g * L, L, dtype=jnp.int32)
        with obs.TRACER.span("train.fused_group", cat="train", k=g,
                             tbptt=True):
            with obs.TRACER.span("train.dispatch", cat="train", k=g,
                                 tbptt=True):
                (self._params, self._updater_state, self._model_state,
                 scores, carries, self._loop, *extras) = step(
                     self._params, self._updater_state, self._model_state,
                     self._loop_state(), features, labels, fmasks, lmasks,
                     carries, t0s)
            from ...common import health as H
            with obs.TRACER.span("train.health", cat="train", k=g):
                rb = H.finish_fused(self, scores,
                                    extras[-1] if emit_health else None, g)
        return carries, t0 + g * L, rb is not None

    def rnn_time_step(self, *features):
        """Single/multi-step streaming inference with carried RNN state
        (reference: ComputationGraph.rnnTimeStep). Returns the list of
        output activations."""
        self._ensure_init()
        if len(features) == 1 and isinstance(features[0], (list, tuple, dict)):
            features = features[0]
        inputs = {n: jnp.asarray(x)
                  for n, x in self._canon_inputs(features).items()}
        single = all(x.ndim == 2 for x in inputs.values())
        if single:
            inputs = {n: x[:, None, :] for n, x in inputs.items()}
        B = int(next(iter(inputs.values())).shape[0])
        state = getattr(self, "_rnn_state", None)
        if state is not None:
            held = next(iter(next(iter(state.values())).values())).shape[0] \
                if state else B
            if held != B:
                raise ValueError(
                    f"rnn_time_step batch size changed ({held} -> {B}); "
                    "call rnn_clear_previous_state() first")
        if state is None:
            self._rnn_state = self._init_carries(B)
        if "rnn_step" not in self._jit_forward:
            def fwd(params, state, inputs, rng, carries):
                acts, _, _, new_carries = self._apply_graph(
                    params, state, inputs, train=False, rng=rng,
                    carries=carries)
                return ([acts[n] for n in self.conf.network_outputs],
                        new_carries)
            self._jit_forward["rnn_step"] = jax.jit(fwd)
        self._rng, rng = jax.random.split(self._rng)
        outs, self._rnn_state = self._jit_forward["rnn_step"](
            self._params, self._model_state, inputs, rng, self._rnn_state)
        if single:
            outs = [o[:, 0] if o.ndim >= 3 else o for o in outs]
        return outs

    rnnTimeStep = rnn_time_step

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    rnnClearPreviousState = rnn_clear_previous_state

    # ------------------------------------------------------------------
    # Inference — reference ComputationGraph.output
    # ------------------------------------------------------------------
    def output(self, *features, train=False, features_masks=None):
        """Returns list of output activations aligned with network_outputs."""
        self._ensure_init()
        if len(features) == 1 and isinstance(features[0], (list, tuple, dict)):
            features = features[0]
        inputs = {n: jnp.asarray(x)
                  for n, x in self._canon_inputs(features).items()}
        fmasks = self._canon_masks(features_masks)
        if fmasks:
            fmasks = {n: jnp.asarray(m) for n, m in fmasks.items()}
        key = ("output", bool(train), fmasks is not None)
        if key not in self._jit_forward:
            def fwd(params, state, inputs, fmasks, rng):
                acts, _, _, _ = self._apply_graph(params, state, inputs,
                                                  train=train, rng=rng,
                                                  fmasks=fmasks)
                return [acts[n] for n in self.conf.network_outputs]
            self._jit_forward[key] = jax.jit(fwd)
        self._rng, rng = jax.random.split(self._rng)
        return self._jit_forward[key](self._params, self._model_state, inputs,
                                      fmasks, rng)

    def feed_forward(self, *features, train=False):
        """Returns dict vertex-name -> activation."""
        self._ensure_init()
        if len(features) == 1 and isinstance(features[0], (list, tuple, dict)):
            features = features[0]
        inputs = {n: jnp.asarray(x)
                  for n, x in self._canon_inputs(features).items()}
        self._rng, rng = jax.random.split(self._rng)
        acts, _, _, _ = self._apply_graph(self._params, self._model_state,
                                          inputs, train=train, rng=rng)
        return acts

    feedForward = feed_forward

    def make_inference_fn(self):
        """PURE inference step `(params, state, x) -> [outputs]` — the
        MultiLayerNetwork.make_inference_fn twin for the serving layer.
        `x` is a single array (single-input graphs — the serving batcher
        coalesces one request tensor) or a dict name->array for
        multi-input graphs. train=False + constant rng: pure in
        (params, state, x), so serving determinism pins hold; params are
        arguments, so hot swap needs no recompile."""
        self._ensure_init()
        in_names = list(self.conf.network_inputs)

        def infer(params, state, x):
            inputs = x if isinstance(x, dict) else {in_names[0]: x}
            rng = jax.random.PRNGKey(0)
            acts, _, _, _ = self._apply_graph(params, state, inputs,
                                              train=False, rng=rng)
            return [acts[n] for n in self.conf.network_outputs]

        return infer

    # ------------------------------------------------------------------
    # Score / gradients (gradient-check compatible API)
    # ------------------------------------------------------------------
    def score(self, data=None, training=False):
        if data is None:
            return float(self._score) if self._score is not None else float("nan")
        self._ensure_init()
        if isinstance(data, DataSet):
            data = _dataset_to_mds(data)
        features = {n: jnp.asarray(f)
                    for n, f in zip(self.conf.network_inputs, data.features)}
        labels = [jnp.asarray(l) for l in data.labels]
        # Honor DataSet/MultiDataSet masks (same as _fit_mds) — dropping them
        # silently skews validation loss on variable-length sequence data.
        fmasks = None
        if data.features_masks:
            fmasks = {n: jnp.asarray(m) if m is not None else None
                      for n, m in zip(self.conf.network_inputs,
                                      data.features_masks)}
        lmasks = None
        if data.labels_masks:
            lmasks = [jnp.asarray(m) if m is not None else None
                      for m in data.labels_masks]
        self._rng, rng = jax.random.split(self._rng)
        s, _ = self._loss_fn(self._params, self._model_state, features, labels,
                             fmasks, lmasks, rng, training)
        return float(s)

    def compute_gradient_and_score(self, features, labels, fmask=None,
                                   lmask=None, train=True):
        self._ensure_init()
        rng = jax.random.PRNGKey(0)
        features = {n: jnp.asarray(f) for n, f in
                    self._canon_inputs(features).items()}
        labels = [jnp.asarray(l) for l in _as_list(labels)]
        fmasks = self._canon_masks(fmask)
        if fmasks:
            fmasks = {n: jnp.asarray(m) for n, m in fmasks.items()}
        lmasks = ([jnp.asarray(m) if m is not None else None
                   for m in _as_list(lmask)] if lmask is not None else None)
        (score, _), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
            self._params, self._model_state, features, labels, fmasks, lmasks,
            rng, train)
        return grads, float(score)

    # ------------------------------------------------------------------
    # Flattened-params contract — reference init:281-345
    # ------------------------------------------------------------------
    def _param_leaves(self):
        leaves = []
        for n in self._layer_names():
            p = self._params[n]
            for k in sorted(p.keys(), key=_param_sort_key):
                leaves.append(((n, k), p[k]))
        return leaves

    def params(self):
        self._ensure_init()
        vecs = [np.asarray(v).ravel() for _, v in self._param_leaves()]
        if not vecs:
            return np.zeros((0,), np.float32)
        return np.concatenate(vecs)

    def set_params(self, flat):
        self._ensure_init()
        flat = np.asarray(flat).ravel()
        offset = 0
        new_params = {n: dict(p) for n, p in self._params.items()}
        for (n, k), v in self._param_leaves():
            sz = int(np.prod(v.shape)) if v.shape else 1
            new_params[n][k] = jnp.asarray(
                flat[offset:offset + sz].reshape(v.shape), v.dtype)
            offset += sz
        if offset != flat.size:
            raise ValueError(f"Expected {offset} params, got {flat.size}")
        self._params = new_params

    setParams = set_params

    def num_params(self):
        return int(sum(int(np.prod(v.shape)) for _, v in self._param_leaves()))

    numParams = num_params

    def unflatten_params(self, flat):
        offset = 0
        out = {n: dict(p) for n, p in self._params.items()}
        for n in self._layer_names():
            p = self._params[n]
            for k in sorted(p.keys(), key=_param_sort_key):
                v = p[k]
                sz = int(np.prod(v.shape)) if v.shape else 1
                out[n][k] = flat[offset:offset + sz].reshape(v.shape).astype(v.dtype)
                offset += sz
        return out

    def make_flat_score_fn(self, features, labels, fmask=None, lmask=None,
                           train=True):
        features = {n: jnp.asarray(f) for n, f in
                    self._canon_inputs(features).items()}
        labels = [jnp.asarray(l) for l in _as_list(labels)]
        fmasks = self._canon_masks(fmask)
        if fmasks:
            fmasks = {n: jnp.asarray(m) for n, m in fmasks.items()}
        lmasks = ([jnp.asarray(m) if m is not None else None
                   for m in _as_list(lmask)] if lmask is not None else None)
        rng = jax.random.PRNGKey(0)

        def score_fn(flat):
            params = self.unflatten_params(flat)
            s, _ = self._loss_fn(params, self._model_state, features, labels,
                                 fmasks, lmasks, rng, train)
            return s

        return jax.jit(score_fn)

    def flatten_gradients(self, grads):
        vecs = []
        for n in self._layer_names():
            p = grads[n]
            for k in sorted(p.keys(), key=_param_sort_key):
                vecs.append(np.asarray(p[k], np.float64).ravel())
        return np.concatenate(vecs) if vecs else np.zeros((0,))

    # ------------------------------------------------------------------
    # Evaluation
    # ------------------------------------------------------------------
    def evaluate(self, data, output_index=0):
        from ...eval.evaluation import Evaluation
        from ...datasets.iterators import (DataSetIterator,
                                           wrap_async_for_fit)
        ev = Evaluation()
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        if isinstance(data, DataSetIterator):
            # full-pass guarantee (the old explicit reset), then stream
            # through the async wrapper (prefetch + staging overlap the
            # eval compute; one batch resident instead of the whole set)
            data.reset()
            data = wrap_async_for_fit(data, self.compute_dtype)
        for ds in data:
            mds = _dataset_to_mds(ds) if isinstance(ds, DataSet) else ds
            outs = self.output(mds.features,
                               features_masks=mds.features_masks)
            lmask = (mds.labels_masks[output_index]
                     if mds.labels_masks else None)
            ev.eval(mds.labels[output_index],
                    np.asarray(outs[output_index]), mask=lmask)
        return ev

    # ------------------------------------------------------------------
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    setListeners = set_listeners

    def clone(self):
        net = ComputationGraph(self.conf.clone())
        if self._params is not None:
            net.init()
            # materialize COPIES: aliasing the live arrays would let the
            # next donated train step delete the clone's buffers with it
            net._params = jax.tree.map(jnp.copy, self._params)
            net._updater_state = jax.tree.map(jnp.copy, self._updater_state)
            net._model_state = jax.tree.map(jnp.copy, self._model_state)
        return net

    def get_layer(self, name):
        return self.conf.vertices[name].conf


def _keeps_time_axis(layer):
    """Whether the layer's output still has the input's time axis (mask
    stays meaningful). Recurrent layers and per-timestep heads do."""
    from ..conf.input_type import RecurrentInputType
    if isinstance(layer, BaseRecurrentLayer):
        return True
    return getattr(layer, "layer_type", "") in ("rnnoutput", "activation",
                                                "dropoutlayer", "batchnorm",
                                                "loss")


def _dataset_to_mds(ds: DataSet) -> MultiDataSet:
    return MultiDataSet(
        [ds.features], [ds.labels],
        [ds.features_mask] if ds.features_mask is not None else None,
        [ds.labels_mask] if ds.labels_mask is not None else None)


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _param_sort_key(k):
    order = {"W": 0, "RW": 1, "b": 2, "gamma": 0, "beta": 1, "vb": 3}
    return (order.get(k, 9), k)
