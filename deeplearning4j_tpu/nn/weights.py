"""Weight initialization.

TPU-native equivalent of the reference's WeightInit enum + WeightInitUtil
(reference: nn/weights/WeightInit.java:28-38, nn/weights/WeightInitUtil.java).

Initializers are pure functions of a jax PRNG key — functional RNG replaces the
reference's global Nd4j RNG so init is reproducible and parallelizable.
fan_in/fan_out follow the reference's conventions (for conv: fan_in =
channels_in * kernel_h * kernel_w).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

VALID = (
    "zero", "ones", "uniform", "xavier", "xavier_uniform", "xavier_fan_in",
    "xavier_legacy", "relu", "relu_uniform", "sigmoid_uniform", "lecun_normal",
    "lecun_uniform", "normal", "distribution", "var_scaling_normal_fan_in",
    "identity",
)


def init(key, shape, fan_in, fan_out, scheme="xavier", distribution=None, dtype=jnp.float32):
    """Create a weight array per the named scheme.

    reference: WeightInitUtil.initWeights — same formulas.
    """
    scheme = str(scheme).lower()
    shape = tuple(int(s) for s in shape)
    fan_in = max(float(fan_in), 1.0)
    fan_out = max(float(fan_out), 1.0)

    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("identity init requires square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "uniform":
        # reference: U(-a, a), a = 1/sqrt(fanIn)
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier":
        # reference: N(0, 2/(fanIn+fanOut))
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "xavier_uniform":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier_fan_in":
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "xavier_legacy":
        std = math.sqrt(1.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme in ("relu", "he_normal"):
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme in ("relu_uniform", "he_uniform"):
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "lecun_normal":
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "lecun_uniform":
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "var_scaling_normal_fan_in":
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "normal":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "distribution":
        if distribution is None:
            raise ValueError("WeightInit 'distribution' requires a distribution spec")
        return _from_distribution(key, shape, distribution, dtype)
    raise ValueError(f"Unknown weight init '{scheme}'. Known: {VALID}")


def _from_distribution(key, shape, dist, dtype):
    """dist: dict like {"type": "normal", "mean": 0, "std": 0.01} or
    {"type": "uniform", "lower": -a, "upper": a} — mirrors the reference's
    nn/conf/distribution/ classes (NormalDistribution, UniformDistribution,
    BinomialDistribution)."""
    kind = str(dist.get("type", "normal")).lower()
    if kind in ("normal", "gaussian"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lo = float(dist.get("lower", -1.0))
        hi = float(dist.get("upper", 1.0))
        return jax.random.uniform(key, shape, dtype, lo, hi)
    if kind == "binomial":
        n = int(dist.get("n", 1))
        p = float(dist.get("p", 0.5))
        return jnp.sum(
            jax.random.bernoulli(key, p, (n,) + shape).astype(dtype), axis=0
        )
    raise ValueError(f"Unknown distribution type '{kind}'")
