"""Activation functions.

TPU-native equivalent of the reference's ND4J ``IActivation`` registry
(reference: deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/NeuralNetConfiguration.java:479
selects the default activation; the activation set mirrors ND4J's Activation enum).

Activations are pure jax functions ``f(x) -> y``; backward passes come from
autodiff rather than the reference's hand-written ``backprop`` methods — XLA
fuses these elementwise ops into adjacent matmuls on the MXU/VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_E = 1e-7


def identity(x):
    return x


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def relu(x):
    return jax.nn.relu(x)


def leakyrelu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, alpha)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def cube(x):
    return x ** 3


def rationaltanh(x):
    # Reference ND4J ActivationRationalTanh: 1.7159 * tanh_approx(2x/3)
    a = 0.6666667 * x
    tanh_approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + jnp.abs(a) + a ** 2 + 1.41645 * a ** 4))
    return 1.7159 * tanh_approx


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def swish(x):
    return jax.nn.swish(x)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def threshold_relu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS = {
    "identity": identity,
    "linear": identity,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "relu": relu,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "softplus": softplus,
    "softsign": softsign,
    "hardtanh": hardtanh,
    "hardsigmoid": hardsigmoid,
    "relu6": relu6,
    "cube": cube,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "swish": swish,
    "mish": mish,
}


def get(name):
    """Resolve an activation by name (case-insensitive) or pass through a callable."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]
