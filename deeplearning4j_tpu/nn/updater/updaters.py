"""Gradient updaters (optimizer state machines) + LR schedules + gradient normalization.

TPU-native equivalent of the reference's updater stack:
- Updater enum + per-variable GradientUpdater mapping (reference:
  nn/conf/Updater.java; nn/updater/LayerUpdater.java:72 update, :240 init)
- LR schedules (reference: nn/conf/LearningRatePolicy.java; applied in
  LayerUpdater.java:130-160)
- Gradient normalization/clipping (reference: nn/conf/GradientNormalization +
  LayerUpdater.java:174-240 preApply)

Design: each updater is a pair of pure functions (init_state, apply) over a
single array; containers vmap-free apply them per-parameter-leaf inside the
jitted train step, so Adam/RMSProp state updates fuse with the gradient
computation in one XLA program (the reference executes them as separate ND4J
ops per variable). State layout is a dict of arrays so the whole optimizer
state is a pytree (checkpointable via ModelSerializer, averageable by
ParallelWrapper exactly as the reference averages updater state,
ParallelWrapper.java:200-212).

All formulas match the reference's ND4J implementations (tested equations
mirror deeplearning4j-core TestUpdaters.java).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Learning rate schedules — reference nn/conf/LearningRatePolicy.java
# ---------------------------------------------------------------------------


def schedule_lr(base_lr, policy, iteration, *, decay_rate=0.0, steps=1.0, power=1.0,
                schedule_map=None, max_iterations=None):
    """Compute the effective learning rate at `iteration` (traced scalar ok).

    Policies: none, exponential, inverse, step, poly, sigmoid, torchstep, schedule.
    Formulas per reference LayerUpdater.applyLrDecayPolicy (LayerUpdater.java:130-160).
    """
    policy = str(policy).lower()
    it = iteration
    if policy in ("none", "fixed"):
        return base_lr
    if policy == "exponential":
        return base_lr * jnp.power(decay_rate, it)
    if policy == "inverse":
        return base_lr / jnp.power(1.0 + decay_rate * it, power)
    if policy == "step":
        return base_lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if policy == "torchstep":
        return base_lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if policy == "poly":
        if max_iterations is None or float(max_iterations) <= 0.0:
            raise ValueError(
                "lr policy 'poly' needs a decay horizon: set "
                ".lr_policy_max_iterations(N) on the builder (lr reaches 0 "
                "at iteration N)")
        frac = jnp.clip(it / float(max_iterations), 0.0, 1.0)
        return base_lr * jnp.power(1.0 - frac, power)
    if policy == "sigmoid":
        return base_lr / (1.0 + jnp.exp(-decay_rate * (it - steps)))
    if policy == "schedule":
        # schedule_map: {iteration: lr} — piecewise-constant; static dict so we
        # unroll into where-chains (small, jit-friendly).
        lr = base_lr
        if schedule_map:
            for k in sorted(schedule_map, key=float):
                lr = jnp.where(it >= float(k), schedule_map[k], lr)
        return lr
    raise ValueError(f"Unknown learning rate policy '{policy}'")


# ---------------------------------------------------------------------------
# Per-array updaters — reference ND4J GradientUpdater impls
# ---------------------------------------------------------------------------

def _zeros_like(p):
    return jnp.zeros_like(p)


def sgd_init(p):
    return {}


def sgd_apply(state, grad, lr, hp):
    return lr * grad, state


def nesterovs_init(p):
    return {"v": _zeros_like(p)}


def nesterovs_apply(state, grad, lr, hp):
    # reference ND4J Nesterovs (TestUpdaters.java:231-234 expectations):
    # vPrev = v; v = mu*v - lr*g; update = mu*vPrev - (1+mu)*v, then
    # params -= update. At mu=0 this reduces to params -= lr*g.
    mu = hp.get("momentum", 0.9)
    v_prev = state["v"]
    v = mu * v_prev - lr * grad
    update = mu * v_prev - (1.0 + mu) * v
    return update, {"v": v}


def adagrad_init(p):
    return {"h": _zeros_like(p)}


def adagrad_apply(state, grad, lr, hp):
    eps = hp.get("epsilon", 1e-6)
    h = state["h"] + grad * grad
    update = lr * grad / (jnp.sqrt(h) + eps)
    return update, {"h": h}


def rmsprop_init(p):
    return {"g2": _zeros_like(p)}


def rmsprop_apply(state, grad, lr, hp):
    decay = hp.get("rmsDecay", 0.95)
    eps = hp.get("epsilon", 1e-8)
    g2 = decay * state["g2"] + (1.0 - decay) * grad * grad
    update = lr * grad / jnp.sqrt(g2 + eps)
    return update, {"g2": g2}


def adadelta_init(p):
    return {"msg": _zeros_like(p), "msdx": _zeros_like(p)}


def adadelta_apply(state, grad, lr, hp):
    rho = hp.get("rho", 0.95)  # reference ND4J AdaDelta default
    eps = hp.get("epsilon", 1e-6)
    msg = rho * state["msg"] + (1.0 - rho) * grad * grad
    dx = grad * jnp.sqrt(state["msdx"] + eps) / jnp.sqrt(msg + eps)
    msdx = rho * state["msdx"] + (1.0 - rho) * dx * dx
    return dx, {"msg": msg, "msdx": msdx}  # note: lr unused, per reference


def _counter_dtype(p):
    # >= f32 so the step counter and bias-correction powers stay exact
    return jnp.promote_types(p.dtype, jnp.float32)


def adam_init(p):
    return {"m": _zeros_like(p), "v": _zeros_like(p),
            "t": jnp.zeros((), _counter_dtype(p))}


def adam_apply(state, grad, lr, hp):
    b1 = hp.get("adamMeanDecay", 0.9)
    b2 = hp.get("adamVarDecay", 0.999)
    eps = hp.get("epsilon", 1e-8)
    t = state["t"] + 1.0
    m = b1 * state["m"] + (1.0 - b1) * grad
    v = b2 * state["v"] + (1.0 - b2) * grad * grad
    alpha = lr * jnp.sqrt(1.0 - jnp.power(b2, t)) / (1.0 - jnp.power(b1, t))
    update = alpha * m / (jnp.sqrt(v) + eps)
    return update, {"m": m, "v": v, "t": t}


def adamax_init(p):
    return {"m": _zeros_like(p), "u": _zeros_like(p),
            "t": jnp.zeros((), _counter_dtype(p))}


def adamax_apply(state, grad, lr, hp):
    b1 = hp.get("adamMeanDecay", 0.9)
    b2 = hp.get("adamVarDecay", 0.999)
    eps = hp.get("epsilon", 1e-8)
    t = state["t"] + 1.0
    m = b1 * state["m"] + (1.0 - b1) * grad
    u = jnp.maximum(b2 * state["u"], jnp.abs(grad))
    update = lr / (1.0 - jnp.power(b1, t)) * m / (u + eps)
    return update, {"m": m, "u": u, "t": t}


def nadam_init(p):
    return {"m": _zeros_like(p), "v": _zeros_like(p),
            "t": jnp.zeros((), _counter_dtype(p))}


def nadam_apply(state, grad, lr, hp):
    b1 = hp.get("adamMeanDecay", 0.9)
    b2 = hp.get("adamVarDecay", 0.999)
    eps = hp.get("epsilon", 1e-8)
    t = state["t"] + 1.0
    m = b1 * state["m"] + (1.0 - b1) * grad
    v = b2 * state["v"] + (1.0 - b2) * grad * grad
    m_hat = m / (1.0 - jnp.power(b1, t + 1.0))
    g_hat = grad / (1.0 - jnp.power(b1, t))
    v_hat = v / (1.0 - jnp.power(b2, t))
    update = lr * (b1 * m_hat + (1.0 - b1) * g_hat) / (jnp.sqrt(v_hat) + eps)
    return update, {"m": m, "v": v, "t": t}


def none_init(p):
    return {}


def none_apply(state, grad, lr, hp):
    return jnp.zeros_like(grad), state


UPDATERS = {
    "sgd": (sgd_init, sgd_apply),
    "nesterovs": (nesterovs_init, nesterovs_apply),
    "adagrad": (adagrad_init, adagrad_apply),
    "rmsprop": (rmsprop_init, rmsprop_apply),
    "adadelta": (adadelta_init, adadelta_apply),
    "adam": (adam_init, adam_apply),
    "adamax": (adamax_init, adamax_apply),
    "nadam": (nadam_init, nadam_apply),
    "none": (none_init, none_apply),
}


def get(name):
    key = str(name).lower()
    if key not in UPDATERS:
        raise ValueError(f"Unknown updater '{name}'. Known: {sorted(UPDATERS)}")
    return UPDATERS[key]


def cast_updater_state(state, dtype):
    """Cast non-scalar float updater-state leaves (Adam m/v, momentum, ...)
    to `dtype` ('bfloat16' to halve optimizer HBM traffic on bandwidth-bound
    steps — see PERF.md). Scalar leaves (the Adam step counter `t`) keep
    their exact dtype. ACCURACY NOTE: bf16 moment estimates lose ~8 bits of
    mantissa; stochastic-rounding-free accumulation of many small gradients
    can stall second-moment growth. Validated for SGD/momentum-class
    training; prefer f32 state (the default) for Adam-family runs where
    final-fraction-of-a-percent accuracy matters."""
    if dtype is None:
        return state
    dt = jnp.dtype(jnp.bfloat16 if str(dtype) == "bfloat16" else dtype)
    return jax.tree.map(
        lambda a: a.astype(dt)
        if (a.ndim > 0 and jnp.issubdtype(a.dtype, jnp.floating)) else a,
        state)


# ---------------------------------------------------------------------------
# Gradient normalization — reference LayerUpdater.preApply (:174-240)
# ---------------------------------------------------------------------------

def normalize_gradients(grads, mode, threshold=1.0):
    """Apply DL4J GradientNormalization to a dict of per-variable gradients.

    Modes: None, RenormalizeL2PerLayer, RenormalizeL2PerParamType,
    ClipElementWiseAbsoluteValue, ClipL2PerLayer, ClipL2PerParamType.
    `grads` is a dict {param_name: array} for one layer.
    """
    if mode is None or str(mode).lower() in ("none", "nogradientnormalization"):
        return grads
    mode_l = str(mode).lower()
    eps = 1e-8
    if mode_l == "renormalizel2perlayer":
        total = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + eps)
        return {k: g / total for k, g in grads.items()}
    if mode_l == "renormalizel2perparamtype":
        return {k: g / (jnp.linalg.norm(g.ravel()) + eps) for k, g in grads.items()}
    if mode_l == "clipelementwiseabsolutevalue":
        return {k: jnp.clip(g, -threshold, threshold) for k, g in grads.items()}
    if mode_l == "clipl2perlayer":
        total = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + eps)
        scale = jnp.minimum(1.0, threshold / total)
        return {k: g * scale for k, g in grads.items()}
    if mode_l == "clipl2perparamtype":
        out = {}
        for k, g in grads.items():
            n = jnp.linalg.norm(g.ravel()) + eps
            out[k] = g * jnp.minimum(1.0, threshold / n)
        return out
    raise ValueError(f"Unknown gradient normalization '{mode}'")
