"""Loss functions.

TPU-native equivalent of ND4J's ``ILossFunction`` family used by the reference's
output layers (reference: nn/conf/layers/OutputLayer.java + ND4J LossFunctions enum;
score computation path MultiLayerNetwork.java:1840 -> IOutputLayer.computeScore).

Each loss is a pure function ``loss(labels, preout, activation_fn, mask) -> per_example``
returning a per-example scalar; containers reduce (mean over examples) and add
L1/L2 terms, matching the reference's score semantics. Gradients come from jax
autodiff (the reference hand-codes computeGradient per loss).

Masking: ``mask`` has shape broadcastable to the per-element loss (e.g. [N,1] or
[N, T] flattened for RNNs) and zeroes out masked elements, matching the
reference's per-output masking (LossUtil.applyMask).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _apply_activation(preout, activation_fn):
    from . import activations
    return activations.get(activation_fn)(preout)


def _reduce_per_example(per_elem, mask):
    """Sum per-element loss over feature axes -> per-example vector. Apply mask first."""
    if mask is not None:
        per_elem = per_elem * mask
    axes = tuple(range(1, per_elem.ndim))
    return jnp.sum(per_elem, axis=axes) if axes else per_elem


def mcxent(labels, preout, activation_fn="softmax", mask=None):
    """Multi-class cross entropy / negative log likelihood.

    When activation is softmax, uses the numerically-stable log_softmax form
    (the reference special-cases softmax the same way in LossMCXENT).
    """
    act = str(activation_fn).lower() if not callable(activation_fn) else ""
    if act == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
        per_elem = -labels * logp
    else:
        out = _apply_activation(preout, activation_fn)
        per_elem = -labels * jnp.log(jnp.clip(out, _EPS, 1.0 - _EPS))
    return _reduce_per_example(per_elem, mask)


negativeloglikelihood = mcxent


def xent(labels, preout, activation_fn="sigmoid", mask=None):
    """Binary cross entropy (elementwise)."""
    act = str(activation_fn).lower() if not callable(activation_fn) else ""
    if act == "sigmoid":
        # stable: max(z,0) - z*y + log(1+exp(-|z|))
        z = preout
        per_elem = jnp.maximum(z, 0) - z * labels + jnp.log1p(jnp.exp(-jnp.abs(z)))
    else:
        out = jnp.clip(_apply_activation(preout, activation_fn), _EPS, 1.0 - _EPS)
        per_elem = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
    return _reduce_per_example(per_elem, mask)


def mse(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    d = out - labels
    per_elem = d * d
    # Reference LossMSE divides by nOut (column-mean) — keep sum over features
    # divided by feature count for parity with DL4J score values.
    n_out = labels.shape[-1]
    return _reduce_per_example(per_elem, mask) / n_out


def l2(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    d = out - labels
    return _reduce_per_example(d * d, mask)


def mae(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    per_elem = jnp.abs(out - labels)
    n_out = labels.shape[-1]
    return _reduce_per_example(per_elem, mask) / n_out


def l1(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    return _reduce_per_example(jnp.abs(out - labels), mask)


def hinge(labels, preout, activation_fn="identity", mask=None):
    """Hinge loss; labels in {-1, +1} (or {0,1} converted by caller)."""
    out = _apply_activation(preout, activation_fn)
    per_elem = jnp.maximum(0.0, 1.0 - labels * out)
    return _reduce_per_example(per_elem, mask)


def squared_hinge(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    per_elem = jnp.maximum(0.0, 1.0 - labels * out) ** 2
    return _reduce_per_example(per_elem, mask)


def kl_divergence(labels, preout, activation_fn="softmax", mask=None):
    out = jnp.clip(_apply_activation(preout, activation_fn), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per_elem = labels * (jnp.log(lab) - jnp.log(out))
    return _reduce_per_example(per_elem, mask)


def poisson(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    per_elem = out - labels * jnp.log(jnp.clip(out, _EPS, None))
    return _reduce_per_example(per_elem, mask)


def mape(labels, preout, activation_fn="identity", mask=None):
    """Mean absolute percentage error: 100 * |y - yhat| / max(|y|, eps),
    column-mean over the output features (reference: nd4j LossMAPE —
    abs-error scaled by abs label, epsilon-clamped so zero labels don't
    produce infinities)."""
    out = _apply_activation(preout, activation_fn)
    per_elem = 100.0 * jnp.abs(out - labels) / jnp.clip(
        jnp.abs(labels), _EPS, None)
    n_out = labels.shape[-1]
    return _reduce_per_example(per_elem, mask) / n_out


def msle(labels, preout, activation_fn="identity", mask=None):
    """Mean squared logarithmic error: (log((y+1)/(yhat+1)))², column-mean
    (reference: nd4j LossMSLE — log1p-ratio squared; inputs expected
    non-negative, clamped at -1+eps so log stays finite)."""
    out = _apply_activation(preout, activation_fn)
    d = (jnp.log1p(jnp.clip(out, _EPS - 1.0, None))
         - jnp.log1p(jnp.clip(labels, _EPS - 1.0, None)))
    n_out = labels.shape[-1]
    return _reduce_per_example(d * d, mask) / n_out


def cosine_proximity(labels, preout, activation_fn="identity", mask=None):
    out = _apply_activation(preout, activation_fn)
    if mask is not None:
        out = out * mask
        labels = labels * mask
    num = jnp.sum(labels * out, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1) + _EPS
    sim = num / den
    r = -sim
    axes = tuple(range(1, r.ndim))
    return jnp.sum(r, axis=axes) if axes else r


LOSSES = {
    "mcxent": mcxent,
    "negativeloglikelihood": mcxent,
    "xent": xent,
    "mse": mse,
    "l2": l2,
    "mae": mae,
    "l1": l1,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "squaredhinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "kld": kl_divergence,
    "mape": mape,
    "msle": msle,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "cosineproximity": cosine_proximity,
}


def get(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(LOSSES)}")
    return LOSSES[key]
