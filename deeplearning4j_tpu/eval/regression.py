"""Regression evaluation.

TPU-native equivalent of reference eval/RegressionEvaluation.java: per-column
MSE, MAE, RMSE, relative squared error, correlation (R), with merge() for
distributed aggregation.
"""
from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns, column_names=None):
        n = int(n_columns)
        self.n_columns = n
        self.column_names = column_names or [f"col_{i}" for i in range(n)]
        self.n = np.zeros(n, np.int64)
        self.sum_abs_err = np.zeros(n)
        self.sum_sq_err = np.zeros(n)
        self.sum_label = np.zeros(n)
        self.sum_sq_label = np.zeros(n)
        self.sum_pred = np.zeros(n)
        self.sum_sq_pred = np.zeros(n)
        self.sum_label_pred = np.zeros(n)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
                labels, predictions = labels[m], predictions[m]
        err = predictions - labels
        self.n += labels.shape[0]
        self.sum_abs_err += np.abs(err).sum(0)
        self.sum_sq_err += (err ** 2).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_sq_label += (labels ** 2).sum(0)
        self.sum_pred += predictions.sum(0)
        self.sum_sq_pred += (predictions ** 2).sum(0)
        self.sum_label_pred += (labels * predictions).sum(0)
        return self

    # -- metrics per column (reference RegressionEvaluation getters) ----
    def mean_squared_error(self, c):
        return self.sum_sq_err[c] / max(self.n[c], 1)

    def mean_absolute_error(self, c):
        return self.sum_abs_err[c] / max(self.n[c], 1)

    def root_mean_squared_error(self, c):
        return float(np.sqrt(self.mean_squared_error(c)))

    def relative_squared_error(self, c):
        n = max(self.n[c], 1)
        mean_label = self.sum_label[c] / n
        ss_tot = self.sum_sq_label[c] - n * mean_label ** 2
        return float(self.sum_sq_err[c] / ss_tot) if ss_tot else float("inf")

    def correlation_r2(self, c):
        n = max(self.n[c], 1)
        cov = self.sum_label_pred[c] - self.sum_label[c] * self.sum_pred[c] / n
        var_l = self.sum_sq_label[c] - self.sum_label[c] ** 2 / n
        var_p = self.sum_sq_pred[c] - self.sum_pred[c] ** 2 / n
        denom = np.sqrt(var_l * var_p)
        return float(cov / denom) if denom else 0.0

    def average_mean_squared_error(self):
        return float(np.mean([self.mean_squared_error(c)
                              for c in range(self.n_columns)]))

    def average_mean_absolute_error(self):
        return float(np.mean([self.mean_absolute_error(c)
                              for c in range(self.n_columns)]))

    def averagerootMeanSquaredError(self):
        return float(np.mean([self.root_mean_squared_error(c)
                              for c in range(self.n_columns)]))

    average_root_mean_squared_error = averagerootMeanSquaredError

    def merge(self, other):
        for attr in ("n", "sum_abs_err", "sum_sq_err", "sum_label",
                     "sum_sq_label", "sum_pred", "sum_sq_pred",
                     "sum_label_pred"):
            setattr(self, attr, getattr(self, attr) + getattr(other, attr))
        return self

    def stats(self):
        lines = [f"{'column':<12}{'MSE':>12}{'MAE':>12}{'RMSE':>12}{'RSE':>12}{'R':>8}"]
        for c in range(self.n_columns):
            lines.append(
                f"{self.column_names[c]:<12}{self.mean_squared_error(c):>12.5g}"
                f"{self.mean_absolute_error(c):>12.5g}"
                f"{self.root_mean_squared_error(c):>12.5g}"
                f"{self.relative_squared_error(c):>12.5g}"
                f"{self.correlation_r2(c):>8.4f}")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()
