"""ROC evaluation: binary ROC + one-vs-all multiclass.

TPU-native equivalent of reference eval/ROC.java (thresholded TPR/FPR curve,
AUC via trapezoid, merge() for distributed aggregation) and
eval/ROCMultiClass.java. Counts accumulate in threshold bins so merge()
across workers is exact, as in the reference.
"""
from __future__ import annotations

import numpy as np


class ROC:
    """Binary ROC. probabilities: P(class=1); labels: 0/1 (or one-hot [N,2])."""

    def __init__(self, threshold_steps=100):
        self.threshold_steps = int(threshold_steps)
        if self.threshold_steps < 1:
            raise ValueError("threshold_steps must be >= 1")
        n = self.threshold_steps + 1
        # per-threshold counts: predicted-positive at threshold t
        self._tp = np.zeros(n, np.int64)
        self._fp = np.zeros(n, np.int64)
        self._pos = 0
        self._neg = 0

    def _thresholds(self):
        return np.linspace(0.0, 1.0, self.threshold_steps + 1)

    def eval(self, labels, probabilities, mask=None):
        labels = np.asarray(labels)
        probs = np.asarray(probabilities)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
        if probs.ndim == 2 and probs.shape[1] == 2:
            probs = probs[:, 1]
        labels = labels.ravel()
        probs = probs.ravel()
        if mask is not None:
            m = np.asarray(mask).astype(bool).ravel()
            labels, probs = labels[m], probs[m]
        pos = labels > 0.5
        self._pos += int(pos.sum())
        self._neg += int((~pos).sum())
        # single pass: bin each score, histogram per class, reversed cumsum
        # gives predicted-positive counts at every threshold at once.
        # bin i counts scores in [t_i, t_{i+1}); prob >= t_i <=> bin >= i.
        S = self.threshold_steps
        bins = np.clip(np.floor(probs * S).astype(np.int64), 0, S)
        pos_hist = np.bincount(bins[pos], minlength=S + 1)
        neg_hist = np.bincount(bins[~pos], minlength=S + 1)
        self._tp += np.cumsum(pos_hist[::-1])[::-1]
        self._fp += np.cumsum(neg_hist[::-1])[::-1]
        return self

    def get_roc_curve(self):
        """-> list of (threshold, fpr, tpr), threshold ascending."""
        out = []
        for i, t in enumerate(self._thresholds()):
            tpr = self._tp[i] / self._pos if self._pos else 0.0
            fpr = self._fp[i] / self._neg if self._neg else 0.0
            out.append((float(t), float(fpr), float(tpr)))
        return out

    getRocCurve = get_roc_curve

    def calculate_auc(self):
        """Trapezoidal AUC over the (fpr, tpr) curve."""
        pts = sorted((fpr, tpr) for _, fpr, tpr in self.get_roc_curve())
        pts = [(1.0, 1.0)] + sorted(pts, reverse=True)  # fpr descending
        auc = 0.0
        for (x1, y1), (x0, y0) in zip(pts, pts[1:]):
            auc += (x1 - x0) * (y1 + y0) / 2.0
        return float(auc)

    calculateAUC = calculate_auc

    def merge(self, other):
        if other.threshold_steps != self.threshold_steps:
            raise ValueError("Cannot merge ROC with different threshold_steps")
        self._tp += other._tp
        self._fp += other._fp
        self._pos += other._pos
        self._neg += other._neg
        return self


class ROCMultiClass:
    """One-vs-all ROC per class. reference: eval/ROCMultiClass.java."""

    def __init__(self, threshold_steps=100):
        self.threshold_steps = int(threshold_steps)
        self._rocs = {}

    def eval(self, labels, probabilities, mask=None):
        labels = np.asarray(labels)
        probs = np.asarray(probabilities)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            probs = probs.reshape(-1, probs.shape[-1])[m]
            mask = None
        C = labels.shape[-1]
        for c in range(C):
            roc = self._rocs.setdefault(c, ROC(self.threshold_steps))
            roc.eval(labels[:, c], probs[:, c], mask)
        return self

    def calculate_auc(self, c):
        return self._rocs[c].calculate_auc()

    calculateAUC = calculate_auc

    def calculate_average_auc(self):
        if not self._rocs:
            return 0.0
        return float(np.mean([r.calculate_auc()
                              for r in self._rocs.values()]))

    calculateAverageAUC = calculate_average_auc

    def get_roc_curve(self, c):
        return self._rocs[c].get_roc_curve()

    def merge(self, other):
        for c, roc in other._rocs.items():
            # merge into a fresh/owned ROC — aliasing the source object
            # would let later eval() calls corrupt both aggregators
            mine = self._rocs.setdefault(c, ROC(self.threshold_steps))
            mine.merge(roc)
        return self
