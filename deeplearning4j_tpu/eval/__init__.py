from .evaluation import ConfusionMatrix, Evaluation
from .regression import RegressionEvaluation
from .roc import ROC, ROCMultiClass

__all__ = ["ConfusionMatrix", "Evaluation", "ROC", "ROCMultiClass",
           "RegressionEvaluation"]
