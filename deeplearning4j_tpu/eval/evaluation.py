"""Classification evaluation.

TPU-native equivalent of reference eval/Evaluation.java:46-780 (eval():191
accumulates confusion counts; stats():352 renders; merge() supports
distributed aggregation as used by Spark eval —
spark/impl/multilayer/evaluation/IEvaluateFlatMapFunction.java).
"""
from __future__ import annotations

import numpy as np


class ConfusionMatrix:
    """reference: eval/ConfusionMatrix.java"""

    def __init__(self, num_classes):
        self.num_classes = int(num_classes)
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual, predicted, count=1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual, predicted):
        return int(self.matrix[actual, predicted])

    def merge(self, other):
        self.matrix += other.matrix
        return self


class Prediction:
    """Per-example prediction record for error analysis — reference
    eval/meta/Prediction.java (actualClass, predictedClass, recordMetaData).
    Only recorded when eval() is called with `meta` (the reference's
    eval(INDArray, INDArray, List<Serializable>) overload)."""

    def __init__(self, actual_class, predicted_class, record_meta_data):
        self.actual_class = int(actual_class)
        self.predicted_class = int(predicted_class)
        self.record_meta_data = record_meta_data

    def get_actual_class(self):
        return self.actual_class

    getActualClass = get_actual_class

    def get_predicted_class(self):
        return self.predicted_class

    getPredictedClass = get_predicted_class

    def get_record_meta_data(self):
        return self.record_meta_data

    getRecordMetaData = get_record_meta_data

    def __repr__(self):
        return (f"Prediction(actualClass={self.actual_class},"
                f"predictedClass={self.predicted_class},"
                f"RecordMetaData={self.record_meta_data})")

    def __eq__(self, other):
        return (isinstance(other, Prediction)
                and self.actual_class == other.actual_class
                and self.predicted_class == other.predicted_class
                and self.record_meta_data == other.record_meta_data)


class Evaluation:
    def __init__(self, num_classes=None, labels=None, top_n=1):
        self.label_names = labels
        self.num_classes = num_classes or (len(labels) if labels else None)
        self.confusion = (ConfusionMatrix(self.num_classes)
                          if self.num_classes else None)
        self.top_n = int(top_n)
        self.top_n_correct = 0
        self.num_examples = 0
        # (actual, predicted) -> [meta, ...] — reference
        # Evaluation.addToMetaConfusionMatrix:938
        self._meta_confusion = {}

    # ------------------------------------------------------------------
    def eval(self, labels, predictions, mask=None, meta=None):
        """labels: one-hot [N,C] (or [N,T,C] sequences); predictions same shape
        of probabilities. reference: Evaluation.eval:191 (+ evalTimeSeries for
        the RNN reshape). `meta`: optional per-example metadata list (len N)
        enabling the Prediction error-analysis queries (reference
        eval(INDArray, INDArray, List<? extends Serializable>))."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if meta is not None and len(meta) != labels.shape[0]:
            raise ValueError(f"meta length {len(meta)} != batch "
                             f"{labels.shape[0]}")
        if labels.ndim == 3:  # [N,T,C] sequence -> flatten valid timesteps
            if meta is not None:  # expand per-sequence meta to timesteps
                meta = [md for md in meta for _ in range(labels.shape[1])]
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
            if meta is not None:
                meta = [x for x, keep in zip(meta, m) if keep]
        elif mask is not None:  # [N,C] with per-example mask
            m = np.asarray(mask).astype(bool).reshape(-1)
            labels = labels[m]
            predictions = predictions[m]
            if meta is not None:
                meta = [x for x, keep in zip(meta, m) if keep]
        if self.num_classes is None:
            self.num_classes = labels.shape[-1]
            self.confusion = ConfusionMatrix(self.num_classes)
        actual = np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        np.add.at(self.confusion.matrix, (actual, pred), 1)
        self.num_examples += len(actual)
        if self.top_n > 1:
            top = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self.top_n_correct += int(np.sum(top == actual[:, None]))
        if meta is not None:
            for a, p, md in zip(actual.tolist(), pred.tolist(), meta):
                self._meta_confusion.setdefault((a, p), []).append(md)
        return self

    # -- Prediction queries (meta-eval only) ---------------------------
    def _predictions(self, pred_filter):
        if not self._meta_confusion:
            return None   # reference returns null without recorded metadata
        out = []
        for (a, p), metas in sorted(self._meta_confusion.items()):
            if pred_filter(a, p):
                out.extend(Prediction(a, p, md) for md in metas)
        return out

    def get_prediction_errors(self):
        """reference Evaluation.getPredictionErrors:961"""
        return self._predictions(lambda a, p: a != p)

    getPredictionErrors = get_prediction_errors

    def get_predictions(self, actual_class, predicted_class):
        """reference Evaluation.getPredictions:1056"""
        return self._predictions(
            lambda a, p: a == actual_class and p == predicted_class)

    getPredictions = get_predictions

    def get_predictions_by_actual_class(self, actual_class):
        return self._predictions(lambda a, p: a == actual_class)

    getPredictionsByActualClass = get_predictions_by_actual_class

    def get_predictions_by_predicted_class(self, predicted_class):
        return self._predictions(lambda a, p: p == predicted_class)

    getPredictionsByPredictedClass = get_predictions_by_predicted_class

    # ------------------------------------------------------------------
    def _tp(self, c):
        return int(self.confusion.matrix[c, c])

    def _fp(self, c):
        return int(self.confusion.matrix[:, c].sum() - self.confusion.matrix[c, c])

    def _fn(self, c):
        return int(self.confusion.matrix[c, :].sum() - self.confusion.matrix[c, c])

    def true_positives(self):
        return {c: self._tp(c) for c in range(self.num_classes)}

    def false_positives(self):
        return {c: self._fp(c) for c in range(self.num_classes)}

    def false_negatives(self):
        return {c: self._fn(c) for c in range(self.num_classes)}

    def accuracy(self):
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def top_n_accuracy(self):
        return (self.top_n_correct / self.num_examples) if self.num_examples else 0.0

    def precision(self, c=None):
        if c is not None:
            tp, fp = self._tp(c), self._fp(c)
            return tp / (tp + fp) if (tp + fp) else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if (self._tp(i) + self._fn(i)) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, c=None):
        if c is not None:
            tp, fn = self._tp(c), self._fn(c)
            return tp / (tp + fn) if (tp + fn) else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if (self._tp(i) + self._fn(i)) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, c=None):
        p, r = self.precision(c), self.recall(c)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    # ------------------------------------------------------------------
    def merge(self, other):
        """Distributed aggregation (reference Evaluation.merge)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(self.num_classes)
        self.confusion.merge(other.confusion)
        self.num_examples += other.num_examples
        self.top_n_correct += other.top_n_correct
        for key, metas in other._meta_confusion.items():
            self._meta_confusion.setdefault(key, []).extend(metas)
        return self

    def stats(self):
        """Render summary (reference Evaluation.stats():352)."""
        lines = ["==========================Scores========================================"]
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines.append("========================================================================")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()
