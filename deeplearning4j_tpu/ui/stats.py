"""Stats collection: StatsListener + report model.

TPU-native equivalent of reference ui-model
stats/BaseStatsListener.java:43 (iterationDone:273-420): per-iteration score,
timing, examples/sec, memory, learning rates, and per-parameter summary
statistics (mean/stdev/mean-magnitude) + histograms of params/gradients/
updates. The SBE wire encoding is replaced by plain dict reports (JSON-able);
routing/storage in ui/storage.py.

TPU note: param statistics require device->host transfers, which are
expensive on remote-attached chips — the collection frequency and the
histogram toggle exist for exactly that reason (the reference has the same
knobs in StatsUpdateConfiguration).
"""
from __future__ import annotations

import time

import numpy as np

from ..optimize.listeners import IterationListener


class StatsUpdateConfiguration:
    """reference: ui-model api/StatsUpdateConfiguration.java"""

    def __init__(self, collect_score=True, collect_timing=True,
                 collect_memory=True, collect_learning_rates=True,
                 collect_histograms=False, histogram_bins=20,
                 collect_mean=True, collect_stdev=True,
                 collect_mean_magnitudes=True, report_frequency=1,
                 collect_activations=False, max_activation_channels=8,
                 max_activation_size=48):
        self.collect_score = collect_score
        self.collect_timing = collect_timing
        self.collect_memory = collect_memory
        self.collect_learning_rates = collect_learning_rates
        self.collect_histograms = collect_histograms
        self.histogram_bins = int(histogram_bins)
        self.collect_mean = collect_mean
        self.collect_stdev = collect_stdev
        self.collect_mean_magnitudes = collect_mean_magnitudes
        self.report_frequency = max(1, int(report_frequency))
        # conv-activation capture (reference ConvolutionalListenerModule /
        # ConvolutionalIterationListener): requires an activation_probe
        # batch on the StatsListener; each report carries normalized
        # per-channel activation grids of every 4-D layer output
        self.collect_activations = collect_activations
        self.max_activation_channels = int(max_activation_channels)
        self.max_activation_size = int(max_activation_size)


def _summary(arr, bins=None):
    a = np.asarray(arr, np.float64).ravel()
    out = {"mean": float(a.mean()) if a.size else 0.0,
           "stdev": float(a.std()) if a.size else 0.0,
           "meanMagnitude": float(np.abs(a).mean()) if a.size else 0.0}
    if bins:
        counts, edges = np.histogram(a, bins=bins)
        out["histogram"] = {"counts": counts.tolist(),
                            "min": float(edges[0]), "max": float(edges[-1])}
    return out


class StatsListener(IterationListener):
    """reference: ui-model stats/BaseStatsListener.java"""

    def __init__(self, router_or_storage, config=None, session_id=None,
                 worker_id="worker_0", activation_probe=None):
        self.router = router_or_storage
        self.config = config or StatsUpdateConfiguration()
        self.session_id = session_id or f"session_{int(time.time() * 1000)}"
        self.worker_id = worker_id
        # small sample batch run through feed_forward when
        # collect_activations is on (the reference listener captures
        # activations from the forward pass itself; the fused TPU step
        # doesn't surface intermediates, so a probe forward collects them)
        self.activation_probe = activation_probe
        self._last_report_time = None
        self._total_examples = 0
        self._total_minibatches = 0
        self._init_sent = False
        self._start_time = time.time()
        self._prev_params = None

    # ------------------------------------------------------------------
    def iteration_done(self, model, iteration):
        c = self.config
        now = time.time()
        self._total_minibatches += 1
        self._total_examples += getattr(model, "_last_batch_size", 0)
        if iteration % c.report_frequency != 0:
            return
        if not self._init_sent:
            self.router.put_static_info(self._static_info(model))
            self._init_sent = True

        report = {"sessionId": self.session_id, "workerId": self.worker_id,
                  "timestamp": int(now * 1000), "iteration": int(iteration)}
        if c.collect_score:
            report["score"] = float(model.score())
        if c.collect_timing:
            if self._last_report_time is not None:
                dt = now - self._last_report_time
                report["iterationTimeMs"] = dt * 1000.0 * c.report_frequency
            total_dt = max(now - self._start_time, 1e-9)
            report["totalRuntimeMs"] = total_dt * 1000.0
            report["examplesPerSecond"] = self._total_examples / total_dt
            report["minibatchesPerSecond"] = self._total_minibatches / total_dt
            report["totalExamples"] = self._total_examples
            report["totalMinibatches"] = self._total_minibatches
            self._last_report_time = now
        if c.collect_memory:
            report["memory"] = self._memory_info()
        if c.collect_learning_rates:
            report["learningRates"] = self._learning_rates(model)
        pol = getattr(model, "_health_policy", None)
        if pol is not None:
            # run-health from the training-health watchdog
            # (common/health.py): skip/spike/rollback/validation-reject
            # counters + the latest event, so the UI can show a run's
            # numerical health next to its score curve
            report["health"] = pol.snapshot()
        if c.collect_mean or c.collect_stdev or c.collect_histograms:
            bins = c.histogram_bins if c.collect_histograms else None
            params = dict(self._param_arrays(model))
            report["parameters"] = {name: _summary(arr, bins)
                                    for name, arr in params.items()}
            # "updates" = param deltas since the last report (reference
            # BaseStatsListener collects update histograms the same way the
            # updater writes them; the delta over report_frequency steps is
            # the TPU-side equivalent without capturing gradients off-device)
            if self._prev_params is not None:
                report["updates"] = {
                    name: _summary(arr - self._prev_params[name], bins)
                    for name, arr in params.items()
                    if name in self._prev_params}
            self._prev_params = params
        if c.collect_activations:
            live = getattr(model, "_last_activation_stats", None)
            live_iter = getattr(model, "_last_activation_stats_iter", None)
            fresh = (live is not None
                     and live_iter != getattr(self, "_last_seen_act_iter",
                                              object()))
            if fresh:
                # the fused step emitted summaries of the REAL training
                # batch (BaseStatsListener.java:273-420 onForwardPass role).
                # Freshness is tracked PER LISTENER by the writing
                # iteration: training modes whose steps don't emit stats
                # (k-local-steps averaging, PS wrapper) must not re-report
                # a stale batch as new data, while a second attached
                # listener still sees the same fresh summaries
                self._last_seen_act_iter = live_iter
                report["activationStats"] = self._live_summaries(live)
                grids = self._live_grids(live)
                if grids:
                    report["activations"] = grids
            elif self.activation_probe is not None:
                # legacy probe path: an extra forward on a user batch
                acts = self._activation_grids(model)
                if acts:
                    report["activations"] = acts
            elif (hasattr(model, "collect_activation_stats")
                  and not getattr(model, "_stats_listener_armed", False)):
                # no probe given: arm the fused step to emit summaries
                # from the next iteration on (one recompile). Armed AT MOST
                # ONCE per model (flag ON the model — an id() set would
                # alias recycled addresses) — if the user later calls
                # collect_activation_stats(False) explicitly, listeners
                # must not silently re-arm it
                model._stats_listener_armed = True
                model.collect_activation_stats(
                    True, c.max_activation_channels, c.max_activation_size)
        self.router.put_update(report)

    # ------------------------------------------------------------------
    def _static_info(self, model):
        import platform

        import jax
        dev = jax.devices()[0]
        return {
            "sessionId": self.session_id,
            "workerId": self.worker_id,
            "startTime": int(self._start_time * 1000),
            "machine": {"hostname": platform.node(),
                        "os": platform.system(),
                        "backend": dev.platform,
                        "device": str(dev)},
            "model": {"class": type(model).__name__,
                      "numParams": int(model.num_params()),
                      "configJson": model.conf.to_json()},
        }

    def _memory_info(self):
        import jax
        out = {}
        try:
            stats = jax.devices()[0].memory_stats() or {}
            out["deviceBytesInUse"] = int(stats.get("bytes_in_use", 0))
            out["deviceBytesLimit"] = int(stats.get("bytes_limit", 0))
        except Exception:
            pass
        try:
            import resource
            out["hostMaxRssKb"] = int(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        except Exception:
            pass
        return out

    def _learning_rates(self, model):
        out = {}
        layers = (model.layers if hasattr(model, "layers")
                  else [s.conf for s in model.conf.vertices.values()
                        if s.is_layer])
        for i, l in enumerate(layers):
            out[getattr(l, "name", None) or str(i)] = float(
                l.learning_rate or 0.0)
        return out

    @staticmethod
    def _live_summaries(live):
        """Scalar per-layer stats from the fused step's on-device
        summaries."""
        return {str(i): {k: float(v) for k, v in s.items() if k != "grid"}
                for i, s in enumerate(live)}

    @staticmethod
    def _norm_grid(g):
        g = np.asarray(g, np.float64)
        lo, hi = float(g.min()), float(g.max())
        return (np.zeros_like(g, np.uint8) if hi <= lo
                else ((g - lo) / (hi - lo) * 255).astype(np.uint8))

    def _live_grids(self, live):
        """Conv activation images from the step-emitted downsampled grids
        (ConvolutionalIterationListener image capture, no probe pass)."""
        out = {}
        for i, s in enumerate(live):
            if "grid" not in s:
                continue
            g = np.asarray(s["grid"])           # [h, w, ch], first example
            grids = [self._norm_grid(g[:, :, ci]).tolist()
                     for ci in range(g.shape[2])]
            if grids:
                out[str(i)] = {"height": len(grids[0]),
                               "width": len(grids[0][0]),
                               "channels": grids}
        return out

    def _activation_grids(self, model):
        """Per-layer activation images for conv layers: first probe example,
        up to max_activation_channels channels, each normalized to 0-255
        (reference ConvolutionalIterationListener image capture)."""
        c = self.config
        acts = model.feed_forward(self.activation_probe, train=False)
        if isinstance(acts, dict):          # ComputationGraph: name -> act
            items = acts.items()
        else:                               # MLN: [input, layer0, ...]
            items = ((str(i - 1), a) for i, a in enumerate(acts) if i > 0)
        out = {}
        for name, a in items:
            a = np.asarray(a)
            if a.ndim != 4:     # NHWC conv maps only
                continue
            a = a[0]            # first example
            h, w, ch = a.shape
            step = max(1, max(h, w) // c.max_activation_size)
            a = a[::step, ::step, :]
            grids = []
            for ci in range(min(ch, c.max_activation_channels)):
                g = a[:, :, ci].astype(np.float64)
                lo, hi = float(g.min()), float(g.max())
                g8 = np.zeros_like(g, np.uint8) if hi <= lo else \
                    ((g - lo) / (hi - lo) * 255).astype(np.uint8)
                grids.append(g8.tolist())
            if grids:
                out[name] = {"height": len(grids[0]),
                             "width": len(grids[0][0]),
                             "channels": grids}
        return out

    def _param_arrays(self, model):
        if isinstance(model._params, dict):     # ComputationGraph
            for name, p in model._params.items():
                for k, v in p.items():
                    yield f"{name}_{k}", np.asarray(v)
        else:                                   # MultiLayerNetwork
            for i, p in enumerate(model._params):
                for k, v in p.items():
                    yield f"{i}_{k}", np.asarray(v)


class ServingStatsReporter:
    """Route serving-layer metrics through the SAME storage path training
    stats use (StatsStorageRouter / ui/storage.py), so the existing UI
    server sees a serving session next to training sessions with zero new
    plumbing. One static-info record names the served model; each
    `report()` appends a timestamped update whose `serving` key carries the
    ServingMetrics snapshot (p50/p99 latency, queue depth, batch occupancy,
    shed/swap counts). The serving loops call `report()` on a cadence the
    server owns (`InferenceServer(stats_reporter=..., report_every=N)`) —
    metrics must never add a per-request host hop."""

    def __init__(self, router_or_storage, session_id=None,
                 worker_id="server_0", model_info=None):
        self.router = router_or_storage
        self.session_id = session_id or f"serving_{int(time.time() * 1000)}"
        self.worker_id = worker_id
        self._model_info = model_info or {}
        self._init_sent = False

    def report(self, snapshot):
        """Append one serving-metrics update (a ServingMetrics.snapshot()
        dict, but any JSON-able mapping works)."""
        if not self._init_sent:
            self.router.put_static_info({
                "sessionId": self.session_id, "workerId": self.worker_id,
                "startTime": int(time.time() * 1000),
                "serving": dict(self._model_info)})
            self._init_sent = True
        self.router.put_update({
            "sessionId": self.session_id, "workerId": self.worker_id,
            "timestamp": int(time.time() * 1000),
            "serving": dict(snapshot)})
