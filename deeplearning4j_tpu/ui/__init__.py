from . import components
from .components import (ChartHistogram, ChartLine, ChartScatter,
                         ChartStackedArea, ChartTimeline, Component,
                         ComponentDiv, ComponentTable, ComponentText,
                         render_html)
from .legacy import (ConvolutionalIterationListener,
                     FlowIterationListener,
                     HistogramIterationListener)
from .server import UIServer
from .stats import StatsListener, StatsUpdateConfiguration
from .storage import (FileStatsStorage, InMemoryStatsStorage,
                      RemoteUIStatsStorageRouter, SqliteStatsStorage,
                      StatsStorageRouter)

__all__ = ["ChartHistogram", "ChartLine", "ChartScatter",
           "ChartStackedArea", "ConvolutionalIterationListener",
           "FlowIterationListener", "HistogramIterationListener",
           "ChartTimeline", "Component", "ComponentDiv", "ComponentTable",
           "ComponentText", "FileStatsStorage", "InMemoryStatsStorage",
           "RemoteUIStatsStorageRouter", "SqliteStatsStorage", "StatsListener",
           "StatsStorageRouter", "StatsUpdateConfiguration", "UIServer",
           "components", "render_html"]
