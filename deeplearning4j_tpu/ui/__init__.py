from .server import UIServer
from .stats import StatsListener, StatsUpdateConfiguration
from .storage import (FileStatsStorage, InMemoryStatsStorage,
                      RemoteUIStatsStorageRouter, StatsStorageRouter)

__all__ = ["FileStatsStorage", "InMemoryStatsStorage",
           "RemoteUIStatsStorageRouter", "StatsListener",
           "StatsStorageRouter", "StatsUpdateConfiguration", "UIServer"]
