"""Legacy UI listeners — one-call training visualization.

TPU-native equivalents of reference deeplearning4j-ui's pre-Play listeners
(ui/weights/HistogramIterationListener.java,
ui/weights/ConvolutionalIterationListener.java,
ui/flow/FlowIterationListener.java): each was an IterationListener that
pushed one kind of visualization to the old UI. Here each is a thin
StatsListener preset that switches on exactly the collection the legacy
listener produced and (optionally) spins up the UIServer page that renders
it — same one-liner ergonomics, modern storage/pages underneath.
"""
from __future__ import annotations

from .stats import StatsListener, StatsUpdateConfiguration
from .storage import InMemoryStatsStorage


def _ensure_storage(storage):
    return storage if storage is not None else InMemoryStatsStorage()


class HistogramIterationListener(StatsListener):
    """Weight/gradient histograms per iteration — reference
    HistogramIterationListener.java (renders at /train/histogram)."""

    def __init__(self, frequency=1, storage=None, bins=20, **kw):
        super().__init__(
            _ensure_storage(storage),
            StatsUpdateConfiguration(collect_histograms=True,
                                     histogram_bins=bins,
                                     report_frequency=frequency), **kw)


class ConvolutionalIterationListener(StatsListener):
    """Per-layer conv activation images — reference
    ConvolutionalIterationListener.java (renders at /train/activations).
    Needs the probe batch the fused step doesn't expose."""

    def __init__(self, activation_probe, frequency=1, storage=None,
                 max_channels=8, **kw):
        super().__init__(
            _ensure_storage(storage),
            StatsUpdateConfiguration(collect_activations=True,
                                     max_activation_channels=max_channels,
                                     report_frequency=frequency),
            activation_probe=activation_probe, **kw)


class FlowIterationListener(StatsListener):
    """Network-topology flow view — reference FlowIterationListener.java.
    The DAG comes from the static-info config snapshot; score/perf update
    per iteration (renders at /train/flow)."""

    def __init__(self, frequency=1, storage=None, **kw):
        super().__init__(
            _ensure_storage(storage),
            StatsUpdateConfiguration(report_frequency=frequency), **kw)
