"""Declarative UI component library — charts/tables/text as JSON.

TPU-native equivalent of reference deeplearning4j-ui-components
(components/chart/{ChartLine,ChartScatter,ChartHistogram,ChartStackedArea,
ChartTimeline}.java, components/table/ComponentTable.java,
components/text/ComponentText.java, ComponentDiv.java): Java objects
serialized to JSON which a JS front-end renders. Here each component is a
small Python object with the same JSON contract (type tag + config), a
from_dict registry for round-trips, and `render_html` which emits a
standalone page rendering every component with the same SVG helpers the
training UI uses (the StatsUtils.exportStatsAsHtml role).
"""
from __future__ import annotations

import json

_REGISTRY = {}


def _register(cls):
    _REGISTRY[cls.type_name] = cls
    return cls


class Component:
    type_name = "Component"

    def to_dict(self):
        raise NotImplementedError

    def to_json(self):
        return json.dumps(self.to_dict())

    toJson = to_json

    @staticmethod
    def from_dict(d):
        cls = _REGISTRY.get(d.get("componentType"))
        if cls is None:
            raise ValueError(f"Unknown component type "
                             f"{d.get('componentType')!r}")
        return cls._from(d)

    @staticmethod
    def from_json(s):
        return Component.from_dict(json.loads(s))

    fromJson = from_json


@_register
class ComponentText(Component):
    """reference: components/text/ComponentText.java"""

    type_name = "ComponentText"

    def __init__(self, text, style=None):
        self.text = str(text)
        self.style = style or {}

    def to_dict(self):
        return {"componentType": self.type_name, "text": self.text,
                "style": self.style}

    @classmethod
    def _from(cls, d):
        return cls(d["text"], d.get("style"))


@_register
class ComponentTable(Component):
    """reference: components/table/ComponentTable.java"""

    type_name = "ComponentTable"

    def __init__(self, header, content, title=None):
        self.header = [str(h) for h in header]
        self.content = [[str(c) for c in row] for row in content]
        self.title = title

    def to_dict(self):
        return {"componentType": self.type_name, "header": self.header,
                "content": self.content, "title": self.title}

    @classmethod
    def _from(cls, d):
        return cls(d["header"], d["content"], d.get("title"))


class _BaseChart(Component):
    def __init__(self, title=None, x_label=None, y_label=None):
        self.title = title
        self.x_label = x_label
        self.y_label = y_label

    def _base_dict(self):
        return {"componentType": self.type_name, "title": self.title,
                "xLabel": self.x_label, "yLabel": self.y_label}


@_register
class ChartLine(_BaseChart):
    """reference: components/chart/ChartLine.java — named series."""

    type_name = "ChartLine"

    def __init__(self, title=None, x_label=None, y_label=None):
        super().__init__(title, x_label, y_label)
        self.series = []    # (name, xs, ys)

    def add_series(self, name, x, y):
        self.series.append((str(name), [float(v) for v in x],
                            [float(v) for v in y]))
        return self

    addSeries = add_series

    def to_dict(self):
        d = self._base_dict()
        d["series"] = [{"name": n, "x": x, "y": y}
                       for n, x, y in self.series]
        return d

    @classmethod
    def _from(cls, d):
        c = cls(d.get("title"), d.get("xLabel"), d.get("yLabel"))
        for s in d.get("series", []):
            c.add_series(s["name"], s["x"], s["y"])
        return c


@_register
class ChartScatter(ChartLine):
    """reference: components/chart/ChartScatter.java"""

    type_name = "ChartScatter"


@_register
class ChartStackedArea(ChartLine):
    """reference: components/chart/ChartStackedArea.java"""

    type_name = "ChartStackedArea"


@_register
class ChartHistogram(_BaseChart):
    """reference: components/chart/ChartHistogram.java — explicit bins."""

    type_name = "ChartHistogram"

    def __init__(self, title=None, x_label=None, y_label=None):
        super().__init__(title, x_label, y_label)
        self.bins = []     # (low, high, count)

    def add_bin(self, low, high, count):
        self.bins.append((float(low), float(high), float(count)))
        return self

    addBin = add_bin

    def to_dict(self):
        d = self._base_dict()
        d["bins"] = [{"low": lo, "high": hi, "count": c}
                     for lo, hi, c in self.bins]
        return d

    @classmethod
    def _from(cls, d):
        c = cls(d.get("title"), d.get("xLabel"), d.get("yLabel"))
        for b in d.get("bins", []):
            c.add_bin(b["low"], b["high"], b["count"])
        return c


@_register
class ChartTimeline(_BaseChart):
    """reference: components/chart/ChartTimeline.java — lanes of
    [start, end, label] entries (the Spark phase-timeline renderer)."""

    type_name = "ChartTimeline"

    def __init__(self, title=None):
        super().__init__(title)
        self.lanes = []    # (lane name, [(start, end, label)])

    def add_lane(self, name, entries):
        self.lanes.append((str(name),
                           [(float(s), float(e), str(lb))
                            for s, e, lb in entries]))
        return self

    addLane = add_lane

    def to_dict(self):
        d = self._base_dict()
        d["lanes"] = [{"name": n,
                       "entries": [{"start": s, "end": e, "label": lb}
                                   for s, e, lb in ents]}
                      for n, ents in self.lanes]
        return d

    @classmethod
    def _from(cls, d):
        c = cls(d.get("title"))
        for lane in d.get("lanes", []):
            c.add_lane(lane["name"],
                       [(e["start"], e["end"], e["label"])
                        for e in lane["entries"]])
        return c


@_register
class ComponentDiv(Component):
    """Container of components — reference ComponentDiv.java."""

    type_name = "ComponentDiv"

    def __init__(self, *children, style=None):
        self.children = list(children)
        self.style = style or {}

    def to_dict(self):
        return {"componentType": self.type_name, "style": self.style,
                "components": [c.to_dict() for c in self.children]}

    @classmethod
    def _from(cls, d):
        return cls(*[Component.from_dict(c)
                     for c in d.get("components", [])],
                   style=d.get("style"))


def render_html(components, title="Components"):
    """Standalone HTML rendering every component — the
    StatsUtils.exportStatsAsHtml role. Data is embedded as JSON and drawn
    client-side with the same safe DOM helpers as the training UI."""
    import html as _html

    from .server import _JS_LIB, _STYLE
    # '<' escaped so an embedded '</script>' in component text cannot
    # terminate the JSON island and inject live HTML into the report
    payload = json.dumps([c.to_dict() for c in components]).replace(
        "<", "\\u003c")
    title = _html.escape(str(title))
    script = _JS_LIB + """
const comps = JSON.parse(document.getElementById('data').textContent);
const root = document.getElementById('root');
function render(c, parent){
 const card = el('div'); card.className='card';
 if(c.title) card.appendChild(el('h2', c.title));
 if(c.componentType==='ComponentText'){
  card.appendChild(el('p', c.text));
 } else if(c.componentType==='ComponentTable'){
  const t=el('table'); const hr=el('tr');
  for(const h of c.header) hr.appendChild(el('th',h));
  t.appendChild(hr);
  for(const row of c.content){const tr=el('tr');
   for(const v of row) tr.appendChild(el('td',v)); t.appendChild(tr);}
  card.appendChild(t);
 } else if(c.componentType==='ChartLine'||c.componentType==='ChartScatter'
           ||c.componentType==='ChartStackedArea'){
  const svg=document.createElementNS('http://www.w3.org/2000/svg','svg');
  card.appendChild(svg);
  const colors=['#06c','#083','#c60','#638','#a40'];
  c.series.forEach((s,i)=>{
   const pts=s.x.map((x,k)=>[x,s.y[k]]);
   if(c.componentType==='ChartScatter') drawScatter(svg, pts);
   else drawLine(svg, pts, colors[i%colors.length]);});
 } else if(c.componentType==='ChartHistogram'){
  const svg=document.createElementNS('http://www.w3.org/2000/svg','svg');
  card.appendChild(svg);
  if(c.bins.length)
   drawHistogram(svg, c.bins.map(b=>b.count), c.bins[0].low,
                 c.bins[c.bins.length-1].high);
 } else if(c.componentType==='ChartTimeline'){
  const t=el('table');
  for(const lane of c.lanes){const tr=el('tr');
   tr.appendChild(el('th', lane.name));
   for(const e of lane.entries)
    tr.appendChild(el('td', e.label+' ['+e.start+'-'+e.end+']'));
   t.appendChild(tr);}
  card.appendChild(t);
 } else if(c.componentType==='ComponentDiv'){
  for(const ch of c.components) render(ch, card);
 }
 parent.appendChild(card);
}
for(const c of comps) render(c, root);
"""
    return (f"<!DOCTYPE html><html><head><title>{title}</title>"
            f"<style>{_STYLE}</style></head><body><div id='root'></div>"
            f"<script type='application/json' id='data'>{payload}</script>"
            f"<script>{script}</script></body></html>")
