"""StatsStorage backends + routing.

TPU-native equivalent of reference ui-model storage/: the StatsStorage API
(sessions, static infos, updates, listeners for live UI push),
InMemoryStatsStorage, FileStatsStorage (JSON-lines replacing MapDB/SQLite),
and RemoteUIStatsStorageRouter (HTTP POST of reports to a remote UI server —
deeplearning4j-core api/storage/impl/RemoteUIStatsStorageRouter.java).
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
import urllib.request


class StatsStorageRouter:
    """Write-side interface (reference: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, info):
        raise NotImplementedError

    def put_update(self, update):
        raise NotImplementedError

    putStaticInfo = put_static_info
    putUpdate = put_update


class BaseStatsStorage(StatsStorageRouter):
    """Read side (what the UI consumes) + listener push.
    reference: api/storage/StatsStorage.java."""

    def __init__(self):
        self._static = {}        # session -> info
        self._updates = {}       # session -> list[update]
        self._listeners = []
        self._lock = threading.Lock()

    # -- write ----------------------------------------------------------
    def put_static_info(self, info):
        with self._lock:
            self._static[info["sessionId"]] = info
        self._notify("static", info)

    def put_update(self, update):
        with self._lock:
            self._updates.setdefault(update["sessionId"], []).append(update)
        self._notify("update", update)

    # -- read -----------------------------------------------------------
    def list_session_ids(self):
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    listSessionIDs = list_session_ids

    def get_static_info(self, session_id):
        return self._static.get(session_id)

    getStaticInfo = get_static_info

    def get_all_updates(self, session_id):
        with self._lock:
            return list(self._updates.get(session_id, []))

    getAllUpdates = get_all_updates

    def get_latest_update(self, session_id):
        ups = self._updates.get(session_id)
        return ups[-1] if ups else None

    getLatestUpdate = get_latest_update

    # -- listeners ------------------------------------------------------
    def register_stats_storage_listener(self, fn):
        self._listeners.append(fn)

    registerStatsStorageListener = register_stats_storage_listener

    def _notify(self, kind, payload):
        for fn in self._listeners:
            try:
                fn(kind, payload)
            except Exception:
                pass


class InMemoryStatsStorage(BaseStatsStorage):
    """reference: ui-model storage/InMemoryStatsStorage.java"""


class FileStatsStorage(BaseStatsStorage):
    """JSON-lines persistence (one record per line, replayed on open) —
    stands in for the reference's FileStatsStorage/MapDB/SQLite backends."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        if os.path.exists(self.path):
            self._replay()

    def _replay(self):
        with open(self.path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec["kind"] == "static":
                    super().put_static_info(rec["data"])
                else:
                    super().put_update(rec["data"])

    def _append(self, kind, data):
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps({"kind": kind, "data": data}) + "\n")

    def put_static_info(self, info):
        self._append("static", info)
        super().put_static_info(info)

    def put_update(self, update):
        self._append("update", update)
        super().put_update(update)


class SqliteStatsStorage(BaseStatsStorage):
    """SQLite-backed persistence — parity with the reference's
    J7FileStatsStorage (ui-model storage/sqlite/J7FileStatsStorage.java):
    a single-file relational store that supports concurrent readers and
    incremental queries, where the JSON-lines FileStatsStorage must replay
    the whole log. Uses stdlib sqlite3 (the reference bundles a JDBC
    driver); updates are indexed by (session, insertion order) so
    `get_updates_since` is a range scan, not a replay."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db_lock = threading.Lock()   # separate from the (non-reentrant)
        #                                    base listener/index lock
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS static_info ("
            " session_id TEXT PRIMARY KEY, data TEXT NOT NULL)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS updates ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " session_id TEXT NOT NULL, data TEXT NOT NULL)")
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_updates_session"
            " ON updates(session_id, id)")
        self._db.commit()
        self._load()

    def _load(self):
        for (data,) in self._db.execute("SELECT data FROM static_info"):
            BaseStatsStorage.put_static_info(self, json.loads(data))
        for (data,) in self._db.execute(
                "SELECT data FROM updates ORDER BY id"):
            BaseStatsStorage.put_update(self, json.loads(data))

    def put_static_info(self, info):
        with self._db_lock:
            self._db.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?, ?)",
                (info["sessionId"], json.dumps(info)))
            self._db.commit()
            super().put_static_info(info)

    def put_update(self, update):
        # both appends under ONE lock so DB rowid order == in-memory list
        # order (get_updates_since's index contract) under concurrent
        # writers; the base _lock nests inside and never takes _db_lock
        with self._db_lock:
            self._db.execute(
                "INSERT INTO updates (session_id, data) VALUES (?, ?)",
                (update["sessionId"], json.dumps(update)))
            self._db.commit()
            super().put_update(update)

    def get_updates_since(self, session_id, after_index):
        """Incremental poll: updates with insertion index > after_index
        (0-based position in get_all_updates order) — the query pattern the
        live UI uses instead of refetching everything."""
        with self._db_lock:
            rows = self._db.execute(
                "SELECT data FROM updates WHERE session_id = ?"
                " ORDER BY id LIMIT -1 OFFSET ?",
                (session_id, int(after_index) + 1)).fetchall()
        return [json.loads(d) for (d,) in rows]

    def close(self):
        self._db.close()


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """POST reports to a remote UI server (reference:
    deeplearning4j-core RemoteUIStatsStorageRouter — used by cluster workers
    to route stats to the driver-side UI)."""

    def __init__(self, url, timeout=5.0):
        self.url = url.rstrip("/")
        self.timeout = float(timeout)

    def _post(self, endpoint, payload):
        data = json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            f"{self.url}{endpoint}", data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.status

    def put_static_info(self, info):
        self._post("/remoteReceive/static", info)

    def put_update(self, update):
        self._post("/remoteReceive/update", update)
