"""UIServer — training visualization web server.

TPU-native equivalent of reference deeplearning4j-play PlayUIServer
(api/UIServer.java:38 — UIServer.getInstance().attach(statsStorage)): a
stdlib http.server replaces the Play framework. Pages (reference
deeplearning4j-play module/ equivalents):

  /                train overview   (TrainModule overview page)
  /train/model     per-layer table + per-param mean-magnitude charts
                   (TrainModule model page)
  /train/histogram param/update histograms (HistogramModule)
  /tsne            t-SNE scatter of uploaded coords (TsneModule)

plus a remote-receiver endpoint accepting POSTed reports from
RemoteUIStatsStorageRouter (reference module/remote/RemoteReceiverModule).

All remote-supplied values are rendered via textContent/createElement (never
innerHTML interpolation) so a process POSTing to /remoteReceive cannot
inject script into the viewer's browser.

Endpoints:
  GET  /api/sessions         session ids
  GET  /api/static/<id>      static info
  GET  /api/updates/<id>     all updates
  GET  /api/tsne/<id>        uploaded t-SNE coords
  POST /api/tsne/<id>        upload t-SNE coords {"coords": [[x,y],..], "labels": [..]}
  POST /remoteReceive/static remote static info
  POST /remoteReceive/update remote update
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_STYLE = """
 body{font-family:sans-serif;margin:2em;background:#fafafa}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:1em;margin-bottom:1em}
 h1{font-size:1.3em} h2{font-size:1.05em;color:#333}
 table{border-collapse:collapse} td,th{padding:2px 10px;text-align:left;
       border-bottom:1px solid #eee}
 svg{width:100%;height:260px}
 nav a{margin-right:1em}
"""

_NAV = """<nav><a href="/">Overview</a><a href="/train/model">Model</a>
<a href="/train/histogram">Histograms</a><a href="/tsne">t-SNE</a></nav>"""

# Shared JS helpers: safe DOM building + line/scatter/histogram rendering.
_JS_LIB = """
function el(tag, text){const e=document.createElement(tag);
 if(text!==undefined) e.textContent=String(text); return e;}
function kvTable(rows){const t=el('table');
 for(const [k,v] of rows){const tr=el('tr');
  tr.appendChild(el('th',k)); tr.appendChild(el('td',v));
  t.appendChild(tr);} return t;}
function drawLine(svg, pts, color){
 svg.textContent='';
 pts = pts.map(p=>[Number(p[0]),Number(p[1])]).filter(p=>isFinite(p[0])&&isFinite(p[1]));
 if(!pts.length) return;
 const W=svg.clientWidth||600, H=svg.clientHeight||260, pad=34;
 const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
 const xmin=Math.min(...xs), xmax=Math.max(...xs);
 const ymin=Math.min(...ys), ymax=Math.max(...ys);
 const X=x=>pad+(x-xmin)/(xmax-xmin||1)*(W-2*pad);
 const Y=y=>H-pad-(y-ymin)/(ymax-ymin||1)*(H-2*pad);
 const ns='http://www.w3.org/2000/svg';
 const pl=document.createElementNS(ns,'polyline');
 pl.setAttribute('fill','none'); pl.setAttribute('stroke',color||'#06c');
 pl.setAttribute('stroke-width','1.5');
 pl.setAttribute('points', pts.map(p=>X(p[0])+','+Y(p[1])).join(' '));
 svg.appendChild(pl);
 const t1=document.createElementNS(ns,'text');
 t1.setAttribute('x',pad); t1.setAttribute('y',12);
 t1.setAttribute('font-size','11'); t1.textContent=ymax.toFixed(5);
 const t2=document.createElementNS(ns,'text');
 t2.setAttribute('x',pad); t2.setAttribute('y',H-8);
 t2.setAttribute('font-size','11'); t2.textContent=ymin.toFixed(5);
 svg.appendChild(t1); svg.appendChild(t2);}
function drawHistogram(svg, counts, lo, hi, color){
 svg.textContent='';
 counts = counts.map(Number);
 const W=svg.clientWidth||600, H=svg.clientHeight||260, pad=30;
 const maxC=Math.max(...counts,1), n=counts.length;
 const ns='http://www.w3.org/2000/svg';
 for(let i=0;i<n;i++){
  const r=document.createElementNS(ns,'rect');
  const bw=(W-2*pad)/n;
  r.setAttribute('x',pad+i*bw); r.setAttribute('width',Math.max(bw-1,1));
  const h=(H-2*pad)*counts[i]/maxC;
  r.setAttribute('y',H-pad-h); r.setAttribute('height',h);
  r.setAttribute('fill',color||'#06c');
  svg.appendChild(r);}
 const t1=document.createElementNS(ns,'text');
 t1.setAttribute('x',pad); t1.setAttribute('y',H-8);
 t1.setAttribute('font-size','11'); t1.textContent=Number(lo).toFixed(4);
 const t2=document.createElementNS(ns,'text');
 t2.setAttribute('x',W-pad-60); t2.setAttribute('y',H-8);
 t2.setAttribute('font-size','11'); t2.textContent=Number(hi).toFixed(4);
 svg.appendChild(t1); svg.appendChild(t2);}
function drawScatter(svg, pts, labels){
 svg.textContent='';
 pts = pts.map(p=>[Number(p[0]),Number(p[1])]).filter(p=>isFinite(p[0])&&isFinite(p[1]));
 if(!pts.length) return;
 const W=svg.clientWidth||600, H=svg.clientHeight||400, pad=20;
 const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
 const xmin=Math.min(...xs), xmax=Math.max(...xs);
 const ymin=Math.min(...ys), ymax=Math.max(...ys);
 const X=x=>pad+(x-xmin)/(xmax-xmin||1)*(W-2*pad);
 const Y=y=>H-pad-(y-ymin)/(ymax-ymin||1)*(H-2*pad);
 const ns='http://www.w3.org/2000/svg';
 for(let i=0;i<pts.length;i++){
  const c=document.createElementNS(ns,'circle');
  c.setAttribute('cx',X(pts[i][0])); c.setAttribute('cy',Y(pts[i][1]));
  c.setAttribute('r','3'); c.setAttribute('fill','#06c');
  svg.appendChild(c);
  if(labels && labels[i]!==undefined){
   const t=document.createElementNS(ns,'text');
   t.setAttribute('x',X(pts[i][0])+4); t.setAttribute('y',Y(pts[i][1])-4);
   t.setAttribute('font-size','9'); t.textContent=String(labels[i]);
   svg.appendChild(t);}}}
async function latestSession(){
 const s=await (await fetch('/api/sessions')).json();
 return s.length? s[s.length-1] : null;}
"""


def _page(title, body, script):
    return (f"<!DOCTYPE html><html><head><title>{title}</title>"
            f"<style>{_STYLE}</style></head><body>{_NAV}"
            f"<h1>{title}</h1>{body}"
            f"<script>{_JS_LIB}{script}</script></body></html>")


_OVERVIEW = _page(
    "Training overview",
    """<div class="card"><h2>Score vs iteration</h2><svg id="chart"></svg></div>
<div class="card"><h2>Performance</h2><div id="perf"></div></div>
<div class="card"><h2>Model</h2><pre id="model"></pre></div>""",
    """
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const ups = await (await fetch('/api/updates/'+sid)).json();
 const st = await (await fetch('/api/static/'+sid)).json();
 if(st && st.model) document.getElementById('model').textContent =
   st.model.class+': '+st.model.numParams+' params on '+
   (st.machine? st.machine.device : '?');
 if(!ups.length) return;
 const last = ups[ups.length-1];
 const perf=document.getElementById('perf'); perf.textContent='';
 perf.appendChild(kvTable([
  ['iteration', last.iteration],
  ['score', Number(last.score||0).toFixed(5)],
  ['examples/sec', Number(last.examplesPerSecond||0).toFixed(1)],
  ['minibatches/sec', Number(last.minibatchesPerSecond||0).toFixed(2)]]));
 const pts = ups.filter(u=>u.score!==undefined).map(u=>[u.iteration,u.score]);
 drawLine(document.getElementById('chart'), pts);
}
refresh(); setInterval(refresh, 2000);""")


_MODEL = _page(
    "Model",
    """<div class="card"><h2>Layers</h2><div id="layers"></div></div>
<div class="card"><h2>Mean magnitude vs iteration
 <select id="param"></select></h2><svg id="mm"></svg></div>""",
    """
let chosen=null;
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const st = await (await fetch('/api/static/'+sid)).json();
 const ups = await (await fetch('/api/updates/'+sid)).json();
 const div=document.getElementById('layers'); div.textContent='';
 if(st && st.model && st.model.configJson){
  try{
   const conf=JSON.parse(st.model.configJson);
   const t=el('table');
   const hd=el('tr'); for(const h of ['#','type','out','activation'])
     hd.appendChild(el('th',h));
   t.appendChild(hd);
   const layers = conf.confs || conf.layers ||
     (conf.vertices? Object.entries(conf.vertices).map(([k,v])=>
        Object.assign({name:k}, v.conf||v)) : []);
   let i=0;
   for(const lc of layers){
    const l = lc.layer || lc;
    const tr=el('tr');
    tr.appendChild(el('td', l.name!==undefined? l.name : i));
    tr.appendChild(el('td', l.type||l['@class']||'?'));
    tr.appendChild(el('td', l.n_out!==undefined? l.n_out:(l.nOut||'')));
    tr.appendChild(el('td', l.activation||''));
    t.appendChild(tr); i++;}
   div.appendChild(t);
  }catch(e){div.appendChild(el('pre','config parse error: '+e));}
 }
 const withP = ups.filter(u=>u.parameters);
 if(!withP.length) return;
 const names = Object.keys(withP[withP.length-1].parameters);
 const sel=document.getElementById('param');
 if(sel.options.length!==names.length){
  sel.textContent='';
  for(const n of names){const o=el('option',n); o.value=n; sel.appendChild(o);}
  sel.onchange=()=>{chosen=sel.value; refresh();};
 }
 const name = chosen || names[0];
 const pts = withP.filter(u=>u.parameters[name])
   .map(u=>[u.iteration, u.parameters[name].meanMagnitude]);
 drawLine(document.getElementById('mm'), pts, '#083');
}
refresh(); setInterval(refresh, 3000);""")


_HISTOGRAM = _page(
    "Histograms",
    """<div class="card"><h2>Parameter <select id="param"></select></h2>
<svg id="hp"></svg></div>
<div class="card"><h2>Update (param delta)</h2><svg id="hu"></svg></div>""",
    """
let chosen=null;
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const ups = await (await fetch('/api/updates/'+sid)).json();
 const withH = ups.filter(u=>u.parameters &&
   Object.values(u.parameters).some(p=>p.histogram));
 if(!withH.length) return;
 const last = withH[withH.length-1];
 const names = Object.keys(last.parameters);
 const sel=document.getElementById('param');
 if(sel.options.length!==names.length){
  sel.textContent='';
  for(const n of names){const o=el('option',n); o.value=n; sel.appendChild(o);}
  sel.onchange=()=>{chosen=sel.value; refresh();};
 }
 const name = chosen || names[0];
 const ph = last.parameters[name] && last.parameters[name].histogram;
 if(ph) drawHistogram(document.getElementById('hp'),
                      ph.counts, ph.min, ph.max);
 const uh = last.updates && last.updates[name] &&
            last.updates[name].histogram;
 if(uh) drawHistogram(document.getElementById('hu'),
                      uh.counts, uh.min, uh.max, '#c60');
}
refresh(); setInterval(refresh, 3000);""")


_TSNE = _page(
    "t-SNE",
    """<div class="card"><h2>Embedding scatter</h2>
<svg id="scatter" style="height:420px"></svg></div>
<div class="card">Upload coords:
 POST /api/tsne/&lt;session&gt; {"coords": [[x,y],...], "labels": [...]}</div>""",
    """
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const r = await fetch('/api/tsne/'+sid);
 if(!r.ok) return;
 const d = await r.json();
 if(d && d.coords) drawScatter(document.getElementById('scatter'),
                               d.coords, d.labels);
}
refresh(); setInterval(refresh, 5000);""")


class _Handler(BaseHTTPRequestHandler):
    storage = None
    tsne = None  # session_id -> {"coords": ..., "labels": ...}

    def log_message(self, *a):   # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page):
        body = page.encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        s = self.storage
        if self.path in ("/", "/train", "/train/overview"):
            self._html(_OVERVIEW)
        elif self.path == "/train/model":
            self._html(_MODEL)
        elif self.path == "/train/histogram":
            self._html(_HISTOGRAM)
        elif self.path == "/tsne":
            self._html(_TSNE)
        elif self.path == "/api/sessions":
            self._json(s.list_session_ids() if s else [])
        elif self.path.startswith("/api/static/"):
            self._json((s.get_static_info(self.path.split("/")[-1]) or {})
                       if s else {})
        elif self.path.startswith("/api/updates/"):
            self._json(s.get_all_updates(self.path.split("/")[-1])
                       if s else [])
        elif self.path.startswith("/api/tsne/"):
            sid = self.path.split("/")[-1]
            data = (self.tsne or {}).get(sid)
            if data is None:
                self._json({"error": "no tsne data"}, 404)
            else:
                self._json(data)
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, UnicodeDecodeError):
            self._json({"error": "bad json"}, 400)
            return
        if self.path.startswith("/api/tsne/"):
            sid = self.path.split("/")[-1]
            if self.tsne is None:
                type(self).tsne = {}
            self.tsne[sid] = {"coords": payload.get("coords", []),
                              "labels": payload.get("labels")}
            self._json({"ok": True})
            return
        if self.storage is None:
            self._json({"error": "no storage attached"}, 503)
            return
        if self.path == "/remoteReceive/static":
            self.storage.put_static_info(payload)
            self._json({"ok": True})
        elif self.path == "/remoteReceive/update":
            self.storage.put_update(payload)
            self._json({"ok": True})
        else:
            self._json({"error": "not found"}, 404)


class UIServer:
    """reference: api/UIServer.java — getInstance().attach(statsStorage)."""

    _instance = None

    def __init__(self, port=9000):
        self.port = int(port)
        self._httpd = None
        self._thread = None
        self.storage = None

    @classmethod
    def get_instance(cls, port=9000):
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    getInstance = get_instance

    def attach(self, storage):
        self.storage = storage
        handler = type("BoundHandler", (_Handler,),
                       {"storage": storage, "tsne": {}})
        if self._httpd is None:
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                              handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.RequestHandlerClass = handler
        return self

    def start(self):
        """Serve without a storage attached (remote-receive-only use);
        POSTs to /remoteReceive return 503 until attach() is called."""
        if self._httpd is None:
            handler = type("BoundHandler", (_Handler,),
                           {"storage": None, "tsne": {}})
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                              handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        UIServer._instance = None
