"""UIServer — training visualization web server.

TPU-native equivalent of reference deeplearning4j-play PlayUIServer
(api/UIServer.java:38 — UIServer.getInstance().attach(statsStorage)): a
stdlib http.server replaces the Play framework. Pages (reference
deeplearning4j-play module/ equivalents):

  /                train overview   (TrainModule overview page)
  /train/model     per-layer table + per-param mean-magnitude charts
                   (TrainModule model page)
  /train/histogram param/update histograms (HistogramModule)
  /train/flow      clickable network DAG (FlowListenerModule)
  /train/activations conv activation grids from the probe batch
                   (ConvolutionalListenerModule)
  /train/system    hardware table + device/host memory charts
                   (TrainModule system tab)
  /tsne            t-SNE scatter of uploaded coords (TsneModule)

plus a remote-receiver endpoint accepting POSTed reports from
RemoteUIStatsStorageRouter (reference module/remote/RemoteReceiverModule),
and a Prometheus text-format route:

  GET /metrics             obs.registry counters/gauges/summaries
                           (serving metrics, PS-transport retries/
                           heartbeats, training-health counters,
                           async-iterator queue depth). Serves the
                           process-wide `obs.default_registry()` unless
                           `attach_metrics(registry)` bound another.

All remote-supplied values are rendered via textContent/createElement (never
innerHTML interpolation) so a process POSTing to /remoteReceive cannot
inject script into the viewer's browser.

Endpoints:
  GET  /api/sessions         session ids
  GET  /api/static/<id>      static info
  GET  /api/updates/<id>     all updates
  GET  /api/tsne/<id>        uploaded t-SNE coords
  POST /api/tsne/<id>        upload t-SNE coords {"coords": [[x,y],..], "labels": [..]}
  POST /remoteReceive/static remote static info
  POST /remoteReceive/update remote update
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_STYLE = """
 body{font-family:sans-serif;margin:2em;background:#fafafa}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:1em;margin-bottom:1em}
 h1{font-size:1.3em} h2{font-size:1.05em;color:#333}
 table{border-collapse:collapse} td,th{padding:2px 10px;text-align:left;
       border-bottom:1px solid #eee}
 svg{width:100%;height:260px}
 nav a{margin-right:1em}
"""

_NAV = """<nav><a href="/">Overview</a><a href="/train/model">Model</a>
<a href="/train/histogram">Histograms</a><a href="/train/flow">Flow</a>
<a href="/train/activations">Activations</a>
<a href="/train/system">System</a><a href="/tsne">t-SNE</a></nav>"""

# Shared JS helpers: safe DOM building + line/scatter/histogram rendering.
_JS_LIB = """
function el(tag, text){const e=document.createElement(tag);
 if(text!==undefined) e.textContent=String(text); return e;}
function kvTable(rows){const t=el('table');
 for(const [k,v] of rows){const tr=el('tr');
  tr.appendChild(el('th',k)); tr.appendChild(el('td',v));
  t.appendChild(tr);} return t;}
function drawLine(svg, pts, color){
 svg.textContent='';
 pts = pts.map(p=>[Number(p[0]),Number(p[1])]).filter(p=>isFinite(p[0])&&isFinite(p[1]));
 if(!pts.length) return;
 const W=svg.clientWidth||600, H=svg.clientHeight||260, pad=34;
 const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
 const xmin=Math.min(...xs), xmax=Math.max(...xs);
 const ymin=Math.min(...ys), ymax=Math.max(...ys);
 const X=x=>pad+(x-xmin)/(xmax-xmin||1)*(W-2*pad);
 const Y=y=>H-pad-(y-ymin)/(ymax-ymin||1)*(H-2*pad);
 const ns='http://www.w3.org/2000/svg';
 const pl=document.createElementNS(ns,'polyline');
 pl.setAttribute('fill','none'); pl.setAttribute('stroke',color||'#06c');
 pl.setAttribute('stroke-width','1.5');
 pl.setAttribute('points', pts.map(p=>X(p[0])+','+Y(p[1])).join(' '));
 svg.appendChild(pl);
 const t1=document.createElementNS(ns,'text');
 t1.setAttribute('x',pad); t1.setAttribute('y',12);
 t1.setAttribute('font-size','11'); t1.textContent=ymax.toFixed(5);
 const t2=document.createElementNS(ns,'text');
 t2.setAttribute('x',pad); t2.setAttribute('y',H-8);
 t2.setAttribute('font-size','11'); t2.textContent=ymin.toFixed(5);
 svg.appendChild(t1); svg.appendChild(t2);}
function drawHistogram(svg, counts, lo, hi, color){
 svg.textContent='';
 counts = counts.map(Number);
 const W=svg.clientWidth||600, H=svg.clientHeight||260, pad=30;
 const maxC=Math.max(...counts,1), n=counts.length;
 const ns='http://www.w3.org/2000/svg';
 for(let i=0;i<n;i++){
  const r=document.createElementNS(ns,'rect');
  const bw=(W-2*pad)/n;
  r.setAttribute('x',pad+i*bw); r.setAttribute('width',Math.max(bw-1,1));
  const h=(H-2*pad)*counts[i]/maxC;
  r.setAttribute('y',H-pad-h); r.setAttribute('height',h);
  r.setAttribute('fill',color||'#06c');
  svg.appendChild(r);}
 const t1=document.createElementNS(ns,'text');
 t1.setAttribute('x',pad); t1.setAttribute('y',H-8);
 t1.setAttribute('font-size','11'); t1.textContent=Number(lo).toFixed(4);
 const t2=document.createElementNS(ns,'text');
 t2.setAttribute('x',W-pad-60); t2.setAttribute('y',H-8);
 t2.setAttribute('font-size','11'); t2.textContent=Number(hi).toFixed(4);
 svg.appendChild(t1); svg.appendChild(t2);}
function drawScatter(svg, pts, labels){
 svg.textContent='';
 pts = pts.map(p=>[Number(p[0]),Number(p[1])]).filter(p=>isFinite(p[0])&&isFinite(p[1]));
 if(!pts.length) return;
 const W=svg.clientWidth||600, H=svg.clientHeight||400, pad=20;
 const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
 const xmin=Math.min(...xs), xmax=Math.max(...xs);
 const ymin=Math.min(...ys), ymax=Math.max(...ys);
 const X=x=>pad+(x-xmin)/(xmax-xmin||1)*(W-2*pad);
 const Y=y=>H-pad-(y-ymin)/(ymax-ymin||1)*(H-2*pad);
 const ns='http://www.w3.org/2000/svg';
 for(let i=0;i<pts.length;i++){
  const c=document.createElementNS(ns,'circle');
  c.setAttribute('cx',X(pts[i][0])); c.setAttribute('cy',Y(pts[i][1]));
  c.setAttribute('r','3'); c.setAttribute('fill','#06c');
  svg.appendChild(c);
  if(labels && labels[i]!==undefined){
   const t=document.createElementNS(ns,'text');
   t.setAttribute('x',X(pts[i][0])+4); t.setAttribute('y',Y(pts[i][1])-4);
   t.setAttribute('font-size','9'); t.textContent=String(labels[i]);
   svg.appendChild(t);}}}
async function latestSession(){
 const s=await (await fetch('/api/sessions')).json();
 return s.length? s[s.length-1] : null;}
function syncSelect(sel, names, chosen, onPick, label){
 // rebuild when the option NAME SET changes (count alone misses a new
 // session with the same number of differently-named layers, leaving
 // the dropdown showing an option that is not what is plotted); returns
 // the active name. A stale choice falls back to names[0], and the
 // widget is synced to whatever is actually plotted.
 const current=[...sel.options].map(o=>o.value);
 if(current.length!==names.length||current.some((v,i)=>v!==names[i])){
  sel.textContent='';
  for(const n of names){const o=el('option', label? label+n : n);
    o.value=n; sel.appendChild(o);}
  sel.onchange=()=>onPick(sel.value);
 }
 const active = names.includes(chosen)? chosen : names[0];
 if(sel.value!==active) sel.value=active;
 return active;}
"""


def _page(title, body, script):
    return (f"<!DOCTYPE html><html><head><title>{title}</title>"
            f"<style>{_STYLE}</style></head><body>{_NAV}"
            f"<h1>{title}</h1>{body}"
            f"<script>{_JS_LIB}{script}</script></body></html>")


_OVERVIEW = _page(
    "Training overview",
    """<div class="card"><h2>Score vs iteration</h2><svg id="chart"></svg></div>
<div class="card"><h2>Performance</h2><div id="perf"></div></div>
<div class="card"><h2>Model</h2><pre id="model"></pre></div>""",
    """
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const ups = await (await fetch('/api/updates/'+sid)).json();
 const st = await (await fetch('/api/static/'+sid)).json();
 if(st && st.model) document.getElementById('model').textContent =
   st.model.class+': '+st.model.numParams+' params on '+
   (st.machine? st.machine.device : '?');
 if(!ups.length) return;
 const last = ups[ups.length-1];
 const perf=document.getElementById('perf'); perf.textContent='';
 perf.appendChild(kvTable([
  ['iteration', last.iteration],
  ['score', Number(last.score||0).toFixed(5)],
  ['examples/sec', Number(last.examplesPerSecond||0).toFixed(1)],
  ['minibatches/sec', Number(last.minibatchesPerSecond||0).toFixed(2)]]));
 const pts = ups.filter(u=>u.score!==undefined).map(u=>[u.iteration,u.score]);
 drawLine(document.getElementById('chart'), pts);
}
refresh(); setInterval(refresh, 2000);""")


_MODEL = _page(
    "Model",
    """<div class="card"><h2>Layers</h2><div id="layers"></div></div>
<div class="card"><h2>Mean magnitude vs iteration
 <select id="param"></select></h2><svg id="mm"></svg></div>
<div class="card" id="actCard" style="display:none">
 <h2>Activation mean magnitude vs iteration
 <select id="actLayer"></select></h2><svg id="am"></svg></div>""",
    """
let chosen=null, chosenAct=null;
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const st = await (await fetch('/api/static/'+sid)).json();
 const ups = await (await fetch('/api/updates/'+sid)).json();
 const div=document.getElementById('layers'); div.textContent='';
 if(st && st.model && st.model.configJson){
  try{
   const conf=JSON.parse(st.model.configJson);
   const t=el('table');
   const hd=el('tr'); for(const h of ['#','type','out','activation'])
     hd.appendChild(el('th',h));
   t.appendChild(hd);
   const layers = conf.confs || conf.layers ||
     (conf.vertices? Object.entries(conf.vertices).map(([k,v])=>
        Object.assign({name:k}, v.conf||v)) : []);
   let i=0;
   for(const lc of layers){
    const l = lc.layer || lc;
    const tr=el('tr');
    tr.appendChild(el('td', l.name!==undefined? l.name : i));
    tr.appendChild(el('td', l.type||l['@class']||'?'));
    tr.appendChild(el('td', l.n_out!==undefined? l.n_out:(l.nOut||'')));
    tr.appendChild(el('td', l.activation||''));
    t.appendChild(tr); i++;}
   div.appendChild(t);
  }catch(e){div.appendChild(el('pre','config parse error: '+e));}
 }
 // live per-layer activation stats (the fused step's on-device
 // summaries of the real training batch — BaseStatsListener role).
 // Drawn BEFORE the param chart: activation-only monitoring
 // (collect_mean/stdev/histograms all False) has no `parameters` key
 // and must not be starved by the param guard below.
 const withA = ups.filter(u=>u.activationStats);
 document.getElementById('actCard').style.display =
   withA.length? '' : 'none';   // re-hide on a session without stats
 if(withA.length){
  const an = syncSelect(document.getElementById('actLayer'),
    Object.keys(withA[withA.length-1].activationStats),
    chosenAct, v=>{chosenAct=v; refresh();}, 'layer ');
  const apts = withA.filter(u=>u.activationStats[an])
    .map(u=>[u.iteration, u.activationStats[an].meanMagnitude]);
  drawLine(document.getElementById('am'), apts, '#705');
 }
 const withP = ups.filter(u=>u.parameters);
 if(!withP.length) return;
 const name = syncSelect(document.getElementById('param'),
   Object.keys(withP[withP.length-1].parameters),
   chosen, v=>{chosen=v; refresh();});
 const pts = withP.filter(u=>u.parameters[name])
   .map(u=>[u.iteration, u.parameters[name].meanMagnitude]);
 drawLine(document.getElementById('mm'), pts, '#083');
}
refresh(); setInterval(refresh, 3000);""")


_HISTOGRAM = _page(
    "Histograms",
    """<div class="card"><h2>Parameter <select id="param"></select>
 — iteration <span id="iterLabel"></span>
 <input type="range" id="iter" min="0" max="0" value="0"
  style="width:300px;vertical-align:middle"></h2>
<svg id="hp"></svg></div>
<div class="card"><h2>Update (param delta)</h2><svg id="hu"></svg></div>""",
    """
let chosen=null, follow=true, withH=[];
function draw(){
 // pure redraw from the cached history — slider drags never refetch
 if(!withH.length) return;
 const slider=document.getElementById('iter');
 const rec = withH[Math.min(Number(slider.value), withH.length-1)];
 document.getElementById('iterLabel').textContent = rec.iteration;
 const names = Object.keys(rec.parameters);
 const sel=document.getElementById('param');
 if(sel.options.length!==names.length){
  sel.textContent='';
  for(const n of names){const o=el('option',n); o.value=n; sel.appendChild(o);}
  sel.onchange=()=>{chosen=sel.value; draw();};
 }
 const name = chosen || names[0];
 const ph = rec.parameters[name] && rec.parameters[name].histogram;
 if(ph) drawHistogram(document.getElementById('hp'),
                      ph.counts, ph.min, ph.max);
 const uh = rec.updates && rec.updates[name] &&
            rec.updates[name].histogram;
 if(uh) drawHistogram(document.getElementById('hu'),
                      uh.counts, uh.min, uh.max, '#c60');
}
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const ups = await (await fetch('/api/updates/'+sid)).json();
 withH = ups.filter(u=>u.parameters &&
   Object.values(u.parameters).some(p=>p.histogram));
 if(!withH.length) return;
 const slider=document.getElementById('iter');
 slider.max = withH.length-1;
 if(follow) slider.value = withH.length-1;
 slider.oninput=()=>{follow=(Number(slider.value)===withH.length-1);
                     draw();};
 draw();
}
refresh(); setInterval(refresh, 3000);""")


_TSNE = _page(
    "t-SNE",
    """<div class="card"><h2>Embedding scatter</h2>
<svg id="scatter" style="height:420px"></svg></div>
<div class="card">Upload coords:
 POST /api/tsne/&lt;session&gt; {"coords": [[x,y],...], "labels": [...]}</div>""",
    """
async function refresh(){
 const sid = await latestSession(); if(!sid) return;
 const r = await fetch('/api/tsne/'+sid);
 if(!r.ok) return;
 const d = await r.json();
 if(d && d.coords) drawScatter(document.getElementById('scatter'),
                               d.coords, d.labels);
}
refresh(); setInterval(refresh, 5000);""")


_FLOW = _page(
    "Flow graph",
    """<div class="card"><h2>Network DAG (click a node)</h2>
<svg id="dag" style="height:460px"></svg></div>
<div class="card"><h2>Selected layer</h2><div id="detail"></div></div>""",
    """
let sel=null;
function buildGraph(conf){
 // returns {nodes:[{name,type,info}], edges:[[from,to]]}
 if(conf.vertices){
  const nodes=[], edges=[];
  for(const inp of (conf.networkInputs||[]))
    nodes.push({name:inp, type:'input', info:{}});
  for(const [k,v] of Object.entries(conf.vertices)){
   const l=v.conf||{};
   nodes.push({name:k, type:(v.kind==='layer'? (l.type||'layer'):'vertex'),
               info:l});
   for(const i of (v.inputs||[])) edges.push([i,k]);
  }
  return {nodes, edges};
 }
 const layers = conf.layers||[];
 const nodes=[{name:'input', type:'input', info:{}}], edges=[];
 let prev='input';
 layers.forEach((l,i)=>{
  const name=String(i);
  nodes.push({name, type:l.type||'layer', info:l});
  edges.push([prev,name]); prev=name;});
 return {nodes, edges};
}
function layerRanks(nodes, edges){
 // longest-path layering
 const rank={}; const indeg={}; const out={};
 nodes.forEach(n=>{rank[n.name]=0; indeg[n.name]=0; out[n.name]=[];});
 edges.forEach(([a,b])=>{indeg[b]++; out[a].push(b);});
 const q=nodes.filter(n=>indeg[n.name]===0).map(n=>n.name);
 while(q.length){
  const u=q.shift();
  for(const v of out[u]){
   rank[v]=Math.max(rank[v], rank[u]+1);
   if(--indeg[v]===0) q.push(v);}}
 return rank;
}
function drawDag(svg, g, params){
 svg.textContent='';
 const ns='http://www.w3.org/2000/svg';
 const rank=layerRanks(g.nodes, g.edges);
 const byRank={};
 g.nodes.forEach(n=>{(byRank[rank[n.name]]=byRank[rank[n.name]]||[]).push(n);});
 const R=Object.keys(byRank).length;
 const W=svg.clientWidth||900, H=svg.clientHeight||460;
 const pos={};
 Object.entries(byRank).forEach(([r,ns_])=>{
  ns_.forEach((n,i)=>{pos[n.name]=[ (Number(r)+0.5)*W/R,
                                    (i+0.5)*H/(ns_.length) ];});});
 for(const [a,b] of g.edges){
  const ln=document.createElementNS(ns,'line');
  ln.setAttribute('x1',pos[a][0]); ln.setAttribute('y1',pos[a][1]);
  ln.setAttribute('x2',pos[b][0]); ln.setAttribute('y2',pos[b][1]);
  ln.setAttribute('stroke','#aaa'); svg.appendChild(ln);}
 for(const n of g.nodes){
  const gr=document.createElementNS(ns,'g');
  const c=document.createElementNS(ns,'rect');
  const [x,y]=pos[n.name];
  c.setAttribute('x',x-44); c.setAttribute('y',y-14);
  c.setAttribute('width',88); c.setAttribute('height',28);
  c.setAttribute('rx',6);
  c.setAttribute('fill', n.type==='input'? '#cde':'#fff');
  c.setAttribute('stroke', sel===n.name? '#c30':'#06c');
  c.setAttribute('stroke-width', sel===n.name? '3':'1.5');
  const t=document.createElementNS(ns,'text');
  t.setAttribute('x',x); t.setAttribute('y',y+4);
  t.setAttribute('text-anchor','middle'); t.setAttribute('font-size','10');
  t.textContent=n.name.length>12? n.name.slice(0,11)+'…' : n.name;
  gr.appendChild(c); gr.appendChild(t);
  gr.style.cursor='pointer';
  gr.onclick=()=>{sel=n.name; showDetail(n, params); refresh();};
  svg.appendChild(gr);}
}
function showDetail(n, params){
 const d=document.getElementById('detail'); d.textContent='';
 const rows=[['name',n.name],['type',n.type]];
 for(const [k,v] of Object.entries(n.info||{}))
  if(v!==null && typeof v!=='object') rows.push([k,v]);
 for(const [pn,ps] of Object.entries(params||{}))
  if(pn.startsWith(n.name+'_'))
   rows.push([pn+' meanMag', Number(ps.meanMagnitude).toExponential(3)]);
 d.appendChild(kvTable(rows));
}
async function refresh(){
 const sid=await latestSession(); if(!sid) return;
 const st=await (await fetch('/api/static/'+sid)).json();
 if(!st || !st.model || !st.model.configJson) return;
 const ups=await (await fetch('/api/updates/'+sid)).json();
 const withP=ups.filter(u=>u.parameters);
 const params=withP.length? withP[withP.length-1].parameters : {};
 try{
  const g=buildGraph(JSON.parse(st.model.configJson));
  drawDag(document.getElementById('dag'), g, params);
 }catch(e){}
}
refresh(); setInterval(refresh, 4000);""")


_ACTIVATIONS = _page(
    "Conv activations",
    """<div class="card"><h2>Layer activations (probe batch, first example)
</h2><div id="grids"></div></div>
<div class="card">Enable with
 StatsUpdateConfiguration(collect_activations=True) and an
 activation_probe batch on the StatsListener.</div>""",
    """
function drawGrid(parent, grid){
 const h=grid.length, w=grid[0].length, scale=Math.max(1, Math.floor(96/Math.max(h,w)));
 const cv=document.createElement('canvas');
 cv.width=w*scale; cv.height=h*scale;
 cv.style.border='1px solid #ccc'; cv.style.margin='2px';
 const ctx=cv.getContext('2d');
 for(let y=0;y<h;y++) for(let x=0;x<w;x++){
  const v=Number(grid[y][x])|0;
  ctx.fillStyle='rgb('+v+','+v+','+v+')';
  ctx.fillRect(x*scale,y*scale,scale,scale);}
 parent.appendChild(cv);}
async function refresh(){
 const sid=await latestSession(); if(!sid) return;
 const ups=await (await fetch('/api/updates/'+sid)).json();
 const withA=ups.filter(u=>u.activations);
 if(!withA.length) return;
 const acts=withA[withA.length-1].activations;
 const root=document.getElementById('grids'); root.textContent='';
 for(const [name,a] of Object.entries(acts)){
  const box=el('div'); box.appendChild(el('h2','layer '+name+' ('+
    a.height+'x'+a.width+', '+a.channels.length+' ch)'));
  for(const g of a.channels) drawGrid(box, g);
  root.appendChild(box);}
}
refresh(); setInterval(refresh, 4000);""")


_SYSTEM = _page(
    "System",
    """<div class="card"><h2>Hardware</h2><div id="hw"></div></div>
<div class="card"><h2>Device memory in use (bytes)</h2><svg id="dm"></svg></div>
<div class="card"><h2>Host max RSS (KB)</h2><svg id="hm"></svg></div>""",
    """
async function refresh(){
 const sid=await latestSession(); if(!sid) return;
 const st=await (await fetch('/api/static/'+sid)).json();
 const hw=document.getElementById('hw'); hw.textContent='';
 if(st && st.machine){
  const rows=Object.entries(st.machine);
  if(st.model) rows.push(['model params', st.model.numParams]);
  hw.appendChild(kvTable(rows));}
 const ups=await (await fetch('/api/updates/'+sid)).json();
 const withM=ups.filter(u=>u.memory);
 drawLine(document.getElementById('dm'),
   withM.filter(u=>u.memory.deviceBytesInUse!==undefined)
        .map(u=>[u.iteration,u.memory.deviceBytesInUse]), '#638');
 drawLine(document.getElementById('hm'),
   withM.filter(u=>u.memory.hostMaxRssKb!==undefined)
        .map(u=>[u.iteration,u.memory.hostMaxRssKb]), '#a40');
}
refresh(); setInterval(refresh, 3000);""")


class _Handler(BaseHTTPRequestHandler):
    storage = None
    tsne = None  # session_id -> {"coords": ..., "labels": ...}
    metrics_registry = None  # None -> obs.default_registry() per request
    metrics_instance = None  # instance label on every /metrics sample

    def log_message(self, *a):   # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, text, code=200):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page):
        body = page.encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        s = self.storage
        if self.path in ("/", "/train", "/train/overview"):
            self._html(_OVERVIEW)
        elif self.path == "/train/model":
            self._html(_MODEL)
        elif self.path == "/train/histogram":
            self._html(_HISTOGRAM)
        elif self.path == "/train/flow":
            self._html(_FLOW)
        elif self.path == "/train/activations":
            self._html(_ACTIVATIONS)
        elif self.path == "/train/system":
            self._html(_SYSTEM)
        elif self.path == "/tsne":
            self._html(_TSNE)
        elif self.path == "/metrics":
            # Prometheus text exposition: the default registry is looked
            # up PER REQUEST (not bound at server start) so counters
            # registered after the UI came up — a serving endpoint built
            # later, the first health event — appear without re-attach
            reg = self.metrics_registry
            if reg is None:
                from ..obs.registry import default_registry
                reg = default_registry()
            self._text(reg.prometheus_text(
                namespace="dl4j_tpu", instance=self.metrics_instance))
        elif self.path == "/api/sessions":
            self._json(s.list_session_ids() if s else [])
        elif self.path.startswith("/api/static/"):
            self._json((s.get_static_info(self.path.split("/")[-1]) or {})
                       if s else {})
        elif self.path.startswith("/api/updates/"):
            self._json(s.get_all_updates(self.path.split("/")[-1])
                       if s else [])
        elif self.path.startswith("/api/tsne/"):
            sid = self.path.split("/")[-1]
            data = (self.tsne or {}).get(sid)
            if data is None:
                self._json({"error": "no tsne data"}, 404)
            else:
                self._json(data)
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, UnicodeDecodeError):
            self._json({"error": "bad json"}, 400)
            return
        if self.path.startswith("/api/tsne/"):
            sid = self.path.split("/")[-1]
            if self.tsne is None:
                type(self).tsne = {}
            self.tsne[sid] = {"coords": payload.get("coords", []),
                              "labels": payload.get("labels")}
            self._json({"ok": True})
            return
        if self.storage is None:
            self._json({"error": "no storage attached"}, 503)
            return
        if self.path == "/remoteReceive/static":
            self.storage.put_static_info(payload)
            self._json({"ok": True})
        elif self.path == "/remoteReceive/update":
            self.storage.put_update(payload)
            self._json({"ok": True})
        else:
            self._json({"error": "not found"}, 404)


class UIServer:
    """reference: api/UIServer.java — getInstance().attach(statsStorage)."""

    _instance = None

    def __init__(self, port=9000):
        self.port = int(port)
        self._httpd = None
        self._thread = None
        self.storage = None
        self.metrics_registry = None
        self.metrics_instance = None

    @classmethod
    def get_instance(cls, port=9000):
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    getInstance = get_instance

    def attach_metrics(self, registry, instance=None):
        """Bind a specific MetricsRegistry to the `/metrics` route
        (default: the process-wide obs.default_registry()). `instance`
        is the federation-friendly replica label: every exposition
        sample gains `instance="..."` so N replicas' scrapes stay
        distinguishable when a fleet view (obs/fleet.py) or a real
        Prometheus aggregates them; None (the default) serves the
        unlabeled byte-identical format — including on a RE-attach, so
        rebinding the route to a new registry never leaks the previous
        registry's label onto the new samples."""
        self.metrics_registry = registry
        self.metrics_instance = (None if instance is None
                                 else str(instance))
        if self._httpd is not None:
            self._httpd.RequestHandlerClass.metrics_registry = registry
            self._httpd.RequestHandlerClass.metrics_instance = \
                self.metrics_instance
        return self

    def attach(self, storage):
        self.storage = storage
        handler = type("BoundHandler", (_Handler,),
                       {"storage": storage, "tsne": {},
                        "metrics_registry": self.metrics_registry,
                        "metrics_instance": self.metrics_instance})
        if self._httpd is None:
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                              handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.RequestHandlerClass = handler
        return self

    def start(self):
        """Serve without a storage attached (remote-receive-only use);
        POSTs to /remoteReceive return 503 until attach() is called."""
        if self._httpd is None:
            handler = type("BoundHandler", (_Handler,),
                           {"storage": None, "tsne": {},
                            "metrics_registry": self.metrics_registry,
                            "metrics_instance": self.metrics_instance})
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                              handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        UIServer._instance = None
