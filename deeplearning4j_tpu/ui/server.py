"""UIServer — training visualization web server.

TPU-native equivalent of reference deeplearning4j-play PlayUIServer
(api/UIServer.java:38 — UIServer.getInstance().attach(statsStorage)): a
stdlib http.server replaces the Play framework. Pages: train overview
(score chart, perf, memory, model info) rendered client-side from the JSON
API; a remote-receiver endpoint accepts POSTed reports from
RemoteUIStatsStorageRouter (reference module/remote/RemoteReceiverModule).

Endpoints:
  GET  /                     overview page (HTML + inline JS chart)
  GET  /api/sessions         session ids
  GET  /api/static/<id>      static info
  GET  /api/updates/<id>     all updates
  POST /remoteReceive/static remote static info
  POST /remoteReceive/update remote update
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!DOCTYPE html>
<html><head><title>DL4J-TPU Training UI</title>
<style>
 body{font-family:sans-serif;margin:2em;background:#fafafa}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:1em;margin-bottom:1em}
 h1{font-size:1.3em} h2{font-size:1.05em;color:#333}
 table{border-collapse:collapse} td,th{padding:2px 10px;text-align:left}
 svg{width:100%;height:260px}
</style></head><body>
<h1>Training overview</h1>
<div class="card"><h2>Score vs iteration</h2><svg id="chart"></svg></div>
<div class="card"><h2>Performance</h2><div id="perf"></div></div>
<div class="card"><h2>Model</h2><pre id="model"></pre></div>
<script>
async function refresh(){
 const sessions = await (await fetch('/api/sessions')).json();
 if(!sessions.length) return;
 const sid = sessions[sessions.length-1];
 const ups = await (await fetch('/api/updates/'+sid)).json();
 const st = await (await fetch('/api/static/'+sid)).json();
 if(st && st.model) document.getElementById('model').textContent =
   st.model.class+': '+st.model.numParams+' params on '+st.machine.device;
 if(!ups.length) return;
 const last = ups[ups.length-1];
 document.getElementById('perf').innerHTML =
  '<table><tr><th>iteration</th><td>'+last.iteration+'</td></tr>'+
  '<tr><th>score</th><td>'+(last.score||0).toFixed(5)+'</td></tr>'+
  '<tr><th>examples/sec</th><td>'+(last.examplesPerSecond||0).toFixed(1)+
  '</td></tr><tr><th>minibatches/sec</th><td>'+
  (last.minibatchesPerSecond||0).toFixed(2)+'</td></tr></table>';
 const pts = ups.filter(u=>u.score!==undefined)
               .map(u=>[u.iteration,u.score]);
 const svg = document.getElementById('chart');
 const W = svg.clientWidth, H = svg.clientHeight, pad=30;
 const xs = pts.map(p=>p[0]), ys = pts.map(p=>p[1]);
 const xmin=Math.min(...xs), xmax=Math.max(...xs)||1;
 const ymin=Math.min(...ys), ymax=Math.max(...ys)||1;
 const X=x=>pad+(x-xmin)/(xmax-xmin||1)*(W-2*pad);
 const Y=y=>H-pad-(y-ymin)/(ymax-ymin||1)*(H-2*pad);
 svg.innerHTML = '<polyline fill="none" stroke="#06c" stroke-width="1.5" '+
  'points="'+pts.map(p=>X(p[0])+','+Y(p[1])).join(' ')+'"/>'+
  '<text x="'+pad+'" y="12" font-size="11">'+ymax.toFixed(4)+'</text>'+
  '<text x="'+pad+'" y="'+(H-8)+'" font-size="11">'+ymin.toFixed(4)+'</text>';
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    storage = None

    def log_message(self, *a):   # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        s = self.storage
        if self.path in ("/", "/train", "/train/overview"):
            body = _PAGE.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/api/sessions":
            self._json(s.list_session_ids() if s else [])
        elif self.path.startswith("/api/static/"):
            self._json(s.get_static_info(self.path.split("/")[-1]) or {})
        elif self.path.startswith("/api/updates/"):
            self._json(s.get_all_updates(self.path.split("/")[-1]))
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(n) or b"{}")
        if self.path == "/remoteReceive/static":
            self.storage.put_static_info(payload)
            self._json({"ok": True})
        elif self.path == "/remoteReceive/update":
            self.storage.put_update(payload)
            self._json({"ok": True})
        else:
            self._json({"error": "not found"}, 404)


class UIServer:
    """reference: api/UIServer.java — getInstance().attach(statsStorage)."""

    _instance = None

    def __init__(self, port=9000):
        self.port = int(port)
        self._httpd = None
        self._thread = None
        self.storage = None

    @classmethod
    def get_instance(cls, port=9000):
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    getInstance = get_instance

    def attach(self, storage):
        self.storage = storage
        handler = type("BoundHandler", (_Handler,), {"storage": storage})
        if self._httpd is None:
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                              handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.RequestHandlerClass = handler
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        UIServer._instance = None
