"""Graph API + storage + loaders.

TPU-native equivalent of reference deeplearning4j-graph:
api/IGraph.java, graph/Graph.java (adjacency-list), data/GraphLoader.java
(delimited edge-list files).
"""
from __future__ import annotations


class Vertex:
    """reference: api/Vertex.java"""

    __slots__ = ("idx", "value")

    def __init__(self, idx, value=None):
        self.idx = int(idx)
        self.value = value

    def __repr__(self):
        return f"Vertex({self.idx}, {self.value!r})"


class Edge:
    """reference: api/Edge.java"""

    __slots__ = ("from_idx", "to_idx", "weight", "directed")

    def __init__(self, from_idx, to_idx, weight=1.0, directed=False):
        self.from_idx = int(from_idx)
        self.to_idx = int(to_idx)
        self.weight = float(weight)
        self.directed = bool(directed)


class Graph:
    """Adjacency-list graph. reference: graph/Graph.java (implements IGraph)."""

    def __init__(self, num_vertices, allow_multiple_edges=True):
        self._vertices = [Vertex(i) for i in range(int(num_vertices))]
        self._adj = [[] for _ in range(int(num_vertices))]   # list[list[Edge]]
        self.allow_multiple_edges = allow_multiple_edges

    def num_vertices(self):
        return len(self._vertices)

    numVertices = num_vertices

    def get_vertex(self, idx):
        return self._vertices[idx]

    getVertex = get_vertex

    def set_vertex_value(self, idx, value):
        self._vertices[idx].value = value

    def add_edge(self, from_idx, to_idx, weight=1.0, directed=False):
        """reference: Graph.addEdge — undirected edges are stored on both
        endpoints."""
        e = Edge(from_idx, to_idx, weight, directed)
        if not self.allow_multiple_edges and any(
                x.to_idx == e.to_idx for x in self._adj[e.from_idx]):
            return
        self._adj[e.from_idx].append(e)
        if not directed and from_idx != to_idx:
            self._adj[e.to_idx].append(Edge(to_idx, from_idx, weight, directed))

    addEdge = add_edge

    def get_edges_out(self, idx):
        return list(self._adj[idx])

    getEdgesOut = get_edges_out

    def get_connected_vertex_indices(self, idx):
        return [e.to_idx for e in self._adj[idx]]

    getConnectedVertexIndices = get_connected_vertex_indices

    def degree(self, idx):
        return len(self._adj[idx])


class GraphLoader:
    """Delimited file loaders. reference: data/GraphLoader.java."""

    @staticmethod
    def load_undirected_graph_edge_list_file(path, num_vertices, delim=","):
        """Each line: `from<delim>to[<delim>weight]`.
        reference: GraphLoader.loadUndirectedGraphEdgeListFile."""
        g = Graph(num_vertices)
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delim)
                w = float(parts[2]) if len(parts) > 2 else 1.0
                g.add_edge(int(parts[0]), int(parts[1]), w, directed=False)
        return g

    loadUndirectedGraphEdgeListFile = load_undirected_graph_edge_list_file

    @staticmethod
    def load_adjacency_list_file(path, delim=","):
        """Each line: `vertex<delim>n1<delim>n2...` (directed edges).
        reference: GraphLoader.loadAdjacencyListFile."""
        rows = []
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                rows.append([int(x) for x in line.split(delim)])
        n = max(max(r) for r in rows) + 1
        g = Graph(n)
        for r in rows:
            for to in r[1:]:
                g.add_edge(r[0], to, directed=True)
        return g
