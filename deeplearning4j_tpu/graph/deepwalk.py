"""DeepWalk — graph vertex embeddings via skip-gram over random walks.

TPU-native equivalent of reference models/deepwalk/DeepWalk.java (skip-gram
with hierarchical softmax over walk sequences, GraphHuffman tree) and the
GraphVectors query API (models/GraphVectors.java) + serializer
(models/loader/GraphVectorSerializer.java). The skip-gram hot loop reuses the
batched XLA kernel from models/embeddings/learning.py.
"""
from __future__ import annotations

import json

import numpy as np

from ..models.sequencevectors.sequence_vectors import SequenceVectors
from .walks import RandomWalkIterator


class DeepWalk:
    class Builder:
        def __init__(self):
            self._vector_size = 100
            self._window = 5
            self._lr = 0.025
            self._seed = 12345
            self._epochs = 1

        def vector_size(self, v):
            self._vector_size = int(v); return self

        vectorSize = vector_size

        def window_size(self, v):
            self._window = int(v); return self

        windowSize = window_size

        def learning_rate(self, v):
            self._lr = float(v); return self

        learningRate = learning_rate

        def seed(self, v):
            self._seed = int(v); return self

        def epochs(self, v):
            self._epochs = int(v); return self

        def build(self):
            dw = DeepWalk()
            dw.vector_size = self._vector_size
            dw.window = self._window
            dw.learning_rate = self._lr
            dw.seed = self._seed
            dw.epochs = self._epochs
            return dw

    def __init__(self):
        self.vector_size = 100
        self.window = 5
        self.learning_rate = 0.025
        self.seed = 12345
        self.epochs = 1
        self._sv = None
        self.num_vertices = 0

    # ------------------------------------------------------------------
    def fit(self, graph_or_walks, walk_length=None):
        """fit(graph, walk_length) generates uniform random walks from every
        vertex; fit(walk_iterator) consumes a prepared iterator.
        reference: DeepWalk.fit(IGraph,int) / fit(GraphWalkIterator)."""
        if walk_length is not None:
            it = RandomWalkIterator(graph_or_walks, walk_length,
                                    seed=self.seed)
            self.num_vertices = graph_or_walks.num_vertices()
        else:
            it = graph_or_walks
            self.num_vertices = it.graph.num_vertices()

        def sequences():
            it.reset()
            while it.has_next():
                yield [str(v) for v in it.next()]

        self._sv = SequenceVectors(
            vector_length=self.vector_size, window=self.window,
            learning_rate=self.learning_rate, seed=self.seed,
            epochs=self.epochs, min_word_frequency=1,
            use_hierarchic_softmax=True)
        self._sv.fit(sequences)
        return self

    # ------------------------------------------------------------------
    # GraphVectors query API
    # ------------------------------------------------------------------
    def get_vertex_vector(self, idx):
        return self._sv.get_word_vector(str(idx))

    getVertexVector = get_vertex_vector

    def similarity(self, a, b):
        return self._sv.similarity(str(a), str(b))

    def verticesNearest(self, idx, top_n=5):
        return [int(w) for w in self._sv.words_nearest(str(idx), top_n)]

    vertices_nearest = verticesNearest

    # ------------------------------------------------------------------
    # serializer — reference: models/loader/GraphVectorSerializer.java
    # ------------------------------------------------------------------
    def save(self, path):
        data = {
            "vectorSize": self.vector_size,
            "numVertices": self.num_vertices,
            "vectors": {w: self._sv.get_word_vector(w).tolist()
                        for w in self._sv.vocab.words()},
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh)

    writeGraphVectors = save

    @staticmethod
    def load(path):
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        dw = DeepWalk()
        dw.vector_size = data["vectorSize"]
        dw.num_vertices = data["numVertices"]
        from ..models.embeddings.lookup_table import InMemoryLookupTable
        from ..models.word2vec.vocab import VocabCache
        vocab = VocabCache()
        n = len(data["vectors"])
        for i, w in enumerate(data["vectors"]):
            vocab.add_token(w, n - i)
        vocab.finish()
        lookup = InMemoryLookupTable(vocab, dw.vector_size)
        lookup.syn0 = np.zeros((len(vocab), dw.vector_size), np.float32)
        for w, vec in data["vectors"].items():
            lookup.syn0[vocab.index_of(w)] = vec
        dw._sv = SequenceVectors(vector_length=dw.vector_size)
        dw._sv.vocab = vocab
        dw._sv.lookup = lookup
        return dw

    loadTxtVectors = load
