"""Random walk iterators.

TPU-native equivalent of reference deeplearning4j-graph iterator/:
RandomWalkIterator, WeightedRandomWalkIterator, NoEdgeHandling modes.
"""
from __future__ import annotations

import numpy as np

SELF_LOOP_ON_DISCONNECTED = "self_loop_on_disconnected"
EXCEPTION_ON_DISCONNECTED = "exception_on_disconnected"


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex.
    reference: iterator/RandomWalkIterator.java."""

    def __init__(self, graph, walk_length, seed=12345,
                 no_edge_handling=SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.seed = int(seed)
        self.no_edge_handling = no_edge_handling
        self.reset()

    def reset(self):
        self._pos = 0
        self._rng = np.random.default_rng(self.seed)

    def has_next(self):
        return self._pos < self.graph.num_vertices()

    hasNext = has_next

    def next(self):
        start = self._pos
        self._pos += 1
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.get_connected_vertex_indices(cur)
            if not nbrs:
                if self.no_edge_handling == EXCEPTION_ON_DISCONNECTED:
                    raise RuntimeError(
                        f"Vertex {cur} has no outgoing edges")
                walk.append(cur)   # self loop
                continue
            cur = int(nbrs[self._rng.integers(0, len(nbrs))])
            walk.append(cur)
        return walk

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks.
    reference: iterator/WeightedRandomWalkIterator.java."""

    def next(self):
        start = self._pos
        self._pos += 1
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            edges = self.graph.get_edges_out(cur)
            if not edges:
                if self.no_edge_handling == EXCEPTION_ON_DISCONNECTED:
                    raise RuntimeError(
                        f"Vertex {cur} has no outgoing edges")
                walk.append(cur)
                continue
            w = np.array([e.weight for e in edges], np.float64)
            p = w / w.sum()
            cur = int(edges[self._rng.choice(len(edges), p=p)].to_idx)
            walk.append(cur)
        return walk
