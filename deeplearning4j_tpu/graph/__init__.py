from .deepwalk import DeepWalk
from .graph import Edge, Graph, GraphLoader, Vertex
from .walks import RandomWalkIterator, WeightedRandomWalkIterator

__all__ = ["DeepWalk", "Edge", "Graph", "GraphLoader", "RandomWalkIterator",
           "Vertex", "WeightedRandomWalkIterator"]
