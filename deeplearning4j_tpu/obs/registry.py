"""MetricsRegistry — the one named surface every subsystem publishes
through.

Before this module each subsystem grew its own ad-hoc counters:
`ServingMetrics` kept a Counter + deques, the training-health policy a
dict, the PS transport logged retries, the async iterator exposed
nothing. The registry generalizes the counter/gauge/reservoir machinery
ServingMetrics proved out into one shared, named, thread-safe store:

  * `Counter`  — monotonically increasing int (requests, retries, sheds,
    dispatches, health skips).
  * `Gauge`    — last-written value (queue depth, slot occupancy).
  * `Reservoir`— bounded deque of recent samples with nearest-rank
    percentiles (latency p50/p99) — RECENT percentiles, not all-time,
    exactly the ServingMetrics window semantics.
  * `Histogram` — FIXED-BUCKET cumulative distribution (Prometheus
    `histogram` kind: `_bucket{le=...}` / `_sum` / `_count`). Unlike a
    reservoir, bucket counts are all-time, mergeable across scrapes /
    processes, and scrape as a real distribution; `quantile()` is the
    classic interpolate-within-bucket estimate — resolution bounded by
    the bucket grid, which is the price of aggregability. The serving
    SLO metrics (TTFT, inter-token latency, the load-sweep read-outs)
    use this kind.

Export surfaces:
  * `snapshot()`        — flat JSON-able dict (the UI-storage shape).
  * `prometheus_text()` — Prometheus text exposition format, served by
    `ui/server.py`'s `/metrics` route (counters as `counter`, gauges as
    `gauge`, reservoirs as `summary` with quantile labels).

Constraints (pinned by tests/test_obs.py):
  * stdlib-only — no jax, no numpy. Publishing a metric can NEVER add a
    device dispatch, and the module stays importable everywhere the
    stdlib-only resilience layer is (numpy-free PS workers).
  * O(1), lock-light hot path: one small lock per metric object, none on
    reads of counters (int read is atomic under the GIL).
"""
from __future__ import annotations

import bisect
import collections
import re
import threading

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_INF_LABEL = 'le="+Inf"'


def sanitize(name):
    """Map an internal dotted metric name onto the Prometheus grammar
    ([a-zA-Z_:][a-zA-Z0-9_:]*): dots/dashes/spaces become underscores."""
    out = _NAME_RE.sub("_", str(name))
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def fmt(v, nd=3):
    """None-safe rounding for metric read-outs: empty reservoirs report
    their percentiles/means as None (no data is not 0.0), and every
    consumer that prints or JSON-encodes a snapshot (tools/serve_ab.py,
    bench.py, tools/obs_report.py) must not crash on the idle case.
    ONE shared helper so the guard cannot drift per call site."""
    if v is None:
        return None
    try:
        return round(float(v), nd)
    except (TypeError, ValueError):
        return v


def percentile(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def bucket_quantile(bounds, counts, q):
    """Interpolated quantile from fixed-bucket counts: `bounds` are the
    finite upper bounds, `counts` the per-bucket counts (an extra final
    entry, the +Inf overflow, is allowed; overflow mass clamps to the
    largest finite bound). Shared by `Histogram.quantile` and the
    loadgen's per-run DELTA quantiles (bucket counts are cumulative and
    subtractable — the property reservoirs lack)."""
    total = sum(counts)
    if total <= 0:
        return None
    target = (q / 100.0) * total
    # signed grids (the admission-error histogram spans negative bounds):
    # the first bucket's lower edge is its own bound, not 0.0 — otherwise
    # interpolation inside a negative first bucket would run BACKWARDS
    # (from 0 down to the bound) and misplace the whole quantile
    cum, lo = 0, min(0.0, bounds[0])
    for i, ub in enumerate(bounds):
        c = counts[i] if i < len(counts) else 0
        if cum + c >= target:
            if c == 0:
                return lo
            return lo + (target - cum) / c * (ub - lo)
        cum += c
        lo = ub
    return bounds[-1]


class Counter:
    """Monotonic counter. `inc` is the only writer."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-written value (None until first set)."""

    __slots__ = ("name", "_value")

    def __init__(self, name):
        self.name = name
        self._value = None

    def set(self, v):
        self._value = v

    @property
    def value(self):
        return self._value


class Reservoir:
    """Bounded sample window with percentile read-out.

    Keeps the most recent `window` samples (deque) so a long-running
    process reports RECENT percentiles; `total` counts every sample ever
    recorded (the Prometheus `_count`)."""

    __slots__ = ("name", "_buf", "_lock", "total")

    def __init__(self, name, window=2048):
        self.name = name
        self._buf = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()
        self.total = 0

    def record(self, v):
        with self._lock:
            self._buf.append(float(v))
            self.total += 1

    def values(self):
        with self._lock:
            return list(self._buf)

    def percentile(self, q):
        return percentile(sorted(self.values()), q)

    def mean(self):
        vals = self.values()
        return (sum(vals) / len(vals)) if vals else None

    def last(self):
        with self._lock:
            return self._buf[-1] if self._buf else None

    def max(self):
        vals = self.values()
        return max(vals) if vals else None


class Histogram:
    """Fixed-bucket cumulative histogram (the Prometheus `histogram`
    kind).

    `buckets` are the FINITE upper bounds (le semantics: a sample lands
    in the first bucket whose bound >= value); everything above the
    largest bound goes to the implicit +Inf bucket. Counts are all-time
    cumulative — two scrapes (or two processes' exposition) can be
    summed bucket-by-bucket, which a Reservoir's sample window can't.

    `quantile(q)` interpolates linearly inside the bucket holding the
    q-th sample (what PromQL's `histogram_quantile()` computes
    server-side): an ESTIMATE whose error is bounded by bucket width.
    Samples past the largest finite bound clamp to that bound."""

    __slots__ = ("name", "buckets", "_counts", "_sum", "total", "_lock")

    # default grid tuned for millisecond latencies: sub-ms inter-token
    # gaps up through multi-second tail requests
    DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                       250, 500, 1000, 2500, 5000, 10000)

    def __init__(self, name, buckets=None):
        self.name = name
        bs = tuple(sorted(float(b) for b in
                          (buckets or self.DEFAULT_BUCKETS)))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)      # last = +Inf overflow
        self._sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self.total += 1

    def _state(self):
        """Atomic (per-bucket counts incl. overflow, sum, total) — the
        exposition must be self-consistent (cumulative counts that sum
        to `_count`), so all three are read under one lock."""
        with self._lock:
            return list(self._counts), self._sum, self.total

    def counts(self):
        return self._state()[0]

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """Estimated q-th percentile (None while empty)."""
        counts, _, _ = self._state()
        return bucket_quantile(self.buckets, counts, q)

    def mean(self):
        _, s, total = self._state()
        return (s / total) if total else None


class MetricsRegistry:
    """Named store of counters/gauges/reservoirs/histograms.

    get-or-create accessors (`counter(name)`, `gauge(name)`,
    `reservoir(name, window)`) so publishers never coordinate creation;
    a name registered as one kind and requested as another raises — a
    rename/typo fails loudly instead of splitting a metric in two."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, name, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def reservoir(self, name, window=2048):
        return self._get(name, Reservoir, window)

    def histogram(self, name, buckets=None):
        """Get-or-create; like `reservoir`'s window, `buckets` only
        applies on first registration (a later caller with a different
        grid gets the existing metric — one name, one grid)."""
        return self._get(name, Histogram, buckets)

    def names(self, prefix=""):
        with self._lock:
            return sorted(n for n in self._metrics if n.startswith(prefix))

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    # -- export surfaces ----------------------------------------------
    def snapshot(self, prefix=""):
        """Flat JSON-able dict: counters/gauges by name, reservoirs as
        `<name>_p50` / `<name>_p99` / `<name>_mean` / `<name>_count`."""
        with self._lock:
            items = [(n, m) for n, m in sorted(self._metrics.items())
                     if n.startswith(prefix)]
        out = {}
        for name, m in items:
            key = name[len(prefix):] if prefix else name
            if isinstance(m, Counter):
                out[key] = m.value
            elif isinstance(m, Gauge):
                out[key] = m.value
            elif isinstance(m, Histogram):
                # ONE atomic state read feeds every derived value, like
                # the exposition path: p50/p99/mean/count must describe
                # the same instant even while another thread observes
                counts, s, total = m._state()
                out[key + "_p50"] = bucket_quantile(m.buckets, counts, 50)
                out[key + "_p99"] = bucket_quantile(m.buckets, counts, 99)
                out[key + "_mean"] = (s / total) if total else None
                out[key + "_count"] = total
            else:
                vals = sorted(m.values())
                out[key + "_p50"] = percentile(vals, 50)
                out[key + "_p99"] = percentile(vals, 99)
                out[key + "_mean"] = (sum(vals) / len(vals)) if vals \
                    else None
                out[key + "_count"] = m.total
        return out

    def kind_snapshot(self, prefix=""):
        """KIND-TAGGED state export — the federation hook
        (obs/fleet.py): unlike `snapshot()`'s flat dict, every entry
        says what it IS, so a merger can apply the correct semantics
        per kind (counters sum, gauges stay per-instance, histogram
        bucket counts add element-wise, summaries don't merge at all).
        Histograms export their full bucket state (bounds + per-bucket
        counts incl. the +Inf overflow + sum + total) from ONE atomic
        read; reservoirs export derived percentiles only — their
        sample windows are NOT aggregable, which is exactly why the
        Histogram kind exists."""
        with self._lock:
            items = [(n, m) for n, m in sorted(self._metrics.items())
                     if n.startswith(prefix)]
        out = {}
        for name, m in items:
            key = name[len(prefix):] if prefix else name
            if isinstance(m, Counter):
                out[key] = {"kind": "counter", "value": m.value}
            elif isinstance(m, Gauge):
                out[key] = {"kind": "gauge", "value": m.value}
            elif isinstance(m, Histogram):
                counts, s, total = m._state()
                out[key] = {"kind": "histogram",
                            "buckets": list(m.buckets),
                            "counts": counts, "sum": s, "total": total}
            else:
                vals = sorted(m.values())
                out[key] = {"kind": "summary",
                            "p50": percentile(vals, 50),
                            "p99": percentile(vals, 99),
                            "mean": (sum(vals) / len(vals)) if vals
                            else None,
                            "count": m.total}
        return out

    def prometheus_text(self, namespace="", instance=None):
        """Prometheus text exposition format (version 0.0.4): counters,
        gauges (skipped while unset), reservoirs as summaries with
        quantile labels. Served by ui/server.py's `/metrics` route.

        `instance` adds an `instance="..."` label to EVERY sample — the
        federation-friendly form: N replicas' expositions stay
        distinguishable after a scrape aggregates them, and
        `obs.fleet.parse_prometheus_text` round-trips it. Default None
        keeps the output byte-identical to the pre-label format."""
        with self._lock:
            items = sorted(self._metrics.items())
        ns = sanitize(namespace) + "_" if namespace else ""
        inst = (None if instance is None else
                str(instance).replace("\\", r"\\").replace('"', r'\"'))

        def lbl(extra=""):
            parts = [p for p in (extra,
                                 f'instance="{inst}"' if inst else "")
                     if p]
            return "{" + ",".join(parts) + "}" if parts else ""

        lines = []
        for name, m in items:
            pname = ns + sanitize(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname}{lbl()} {m.value}")
            elif isinstance(m, Gauge):
                if m.value is None:
                    continue
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname}{lbl()} {float(m.value)}")
            elif isinstance(m, Histogram):
                counts, total_sum, _ = m._state()
                lines.append(f"# TYPE {pname} histogram")
                cum = 0
                for b, c in zip(m.buckets, counts):
                    cum += c
                    le = 'le="%g"' % b
                    lines.append(f"{pname}_bucket{lbl(le)} {cum}")
                # +Inf closes over the SAME atomic state read, so the
                # exposition is always internally consistent
                lines.append(
                    f"{pname}_bucket{lbl(_INF_LABEL)} {sum(counts)}")
                lines.append(f"{pname}_sum{lbl()} {total_sum}")
                lines.append(f"{pname}_count{lbl()} {sum(counts)}")
            else:
                vals = sorted(m.values())
                lines.append(f"# TYPE {pname} summary")
                for q, label in ((50, "0.5"), (90, "0.9"), (99, "0.99")):
                    v = percentile(vals, q)
                    if v is not None:
                        qlbl = 'quantile="%s"' % label
                        lines.append(f"{pname}{lbl(qlbl)} {v}")
                lines.append(f"{pname}_count{lbl()} {m.total}")
        return "\n".join(lines) + "\n"


_default = MetricsRegistry()
_default_lock = threading.Lock()


def default_registry():
    """The process-wide registry: PS-transport retries, async-iterator
    queue depth, training-health counters, and any ServingMetrics built
    without an explicit registry all publish here, and ui/server.py's
    `/metrics` route serves it by default."""
    return _default


def reset_default_registry():
    """Swap in a fresh default registry (tests: isolate counters)."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
    return _default
