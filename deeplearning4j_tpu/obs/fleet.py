"""Fleet observability plane: metrics federation, trace stitching, and
the autoscaling signal as a tested object.

A router over N decode replicas (the ROADMAP's replica-fleet item)
needs three things no single-process module provided:

  * **Metrics federation** — `FleetView` merges N instances' metric
    state with KIND-CORRECT semantics, the table every derived fleet
    read-out rests on:

        counter    SUM across instances (requests, tokens, sheds)
        gauge      kept PER-INSTANCE + min/mean/max aggregate (a
                   queue-depth or service-rate gauge summed across
                   replicas is meaningful only as an explicit derived
                   read-out, never silently; averaged into a counter,
                   never)
        histogram  bucket counts add ELEMENT-WISE (same fixed grid) —
                   the aggregability the PR 7 fixed-bucket design
                   exists for: the merged `bucket_quantile` equals the
                   quantile of a histogram that observed the pooled
                   samples, exactly, because the merged counts ARE that
                   histogram's counts
        summary    (reservoir percentiles) kept per-instance only —
                   sample windows are not aggregable, which is exactly
                   why the Histogram kind exists

    Sources are in-process (`ServingMetrics.kind_snapshot()` /
    `MetricsRegistry.kind_snapshot()`) or a parsed `/metrics`
    Prometheus text exposition (`parse_prometheus_text`) — the same
    merge code serves a unit test and a real scrape. Derived fleet
    read-outs (fleet SLO attainment, fleet goodput-under-SLO,
    per-instance shed share) are computed FROM the merged state, never
    re-sampled.

  * **Trace stitching** — `merge_traces` aligns N saved Chrome traces
    by their `clock_sync` wall-clock anchors (PR 7) into ONE
    Perfetto-loadable file with per-instance process groups (distinct
    `pid` + `process_name` metadata). With the `TraceContext` that
    rides a migrated request's artifact (obs/trace.py +
    serving/kvstate.py), a request moved between servers reads as a
    single timeline: enqueue -> decode on A -> spill -> resume on B,
    same trace id, two process groups.

  * **`AutoscaleSignal`** — the ROADMAP recipe ("shed rising while
    service rate is flat = add replicas, not queue") promoted from
    prose to a windowed, hysteresis-bounded detector over merged fleet
    snapshots:

        sheds accruing + service NOT rising   -> scale_up   (capacity:
                                                 flat = exhausted,
                                                 sagging = degrading
                                                 under overload —
                                                 measured: the
                                                 admission estimator's
                                                 rate drops ~2x past
                                                 the knee)
        sheds accruing + service rate RISING  -> hold       (queue —
                                                 capacity still
                                                 ramping, adding
                                                 replicas would chase a
                                                 transient)
        sheds quiet + flat + LOW occupancy    -> scale_down
        anything else / warm-up               -> hold

    A decision only changes after `hysteresis` consecutive identical
    raw verdicts, so a single-window blip can never flap the fleet.
    The detector is pure state-in/decision-out (no clock, no rng):
    seeded synthetic traces pin it deterministically
    (tests/test_fleet.py).

Like the rest of obs/, this module is STDLIB-ONLY — it never imports
jax or numpy (the structural no-device-dispatch pin covers every file
in the package), so federating a fleet's metrics can never add a
device dispatch to any serving path.
"""
from __future__ import annotations

import collections
import re

from .registry import bucket_quantile

__all__ = ["FleetView", "AutoscaleSignal", "parse_prometheus_text",
           "merge_traces", "SHED_KEYS"]


# ---------------------------------------------------------------------------
# Prometheus text exposition -> kind snapshot
# ---------------------------------------------------------------------------
_TYPE_RE = re.compile(r"^#\s*TYPE\s+(\S+)\s+(\S+)\s*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _labels(s):
    return {m.group(1): m.group(2).replace(r"\"", '"')
            .replace(r"\\", "\\")
            for m in _LABEL_RE.finditer(s or "")}


def _num(s):
    v = float(s)
    return int(v) if v == int(v) and "e" not in s.lower() \
        and "." not in s else v


def parse_prometheus_text(text, strip_prefix="", instance=None):
    """Parse a `/metrics` text exposition (the format
    `MetricsRegistry.prometheus_text` emits, `instance` label included
    or not) back into the kind-tagged snapshot shape
    `MetricsRegistry.kind_snapshot` produces — so `FleetView` merges a
    real scrape and an in-process registry through ONE code path.

    Histogram cumulative `_bucket{le=}` samples are de-cumulated back
    to per-bucket counts (the +Inf bucket becomes the overflow entry);
    summaries keep their quantiles per-instance (not mergeable).
    `strip_prefix` removes a namespace prefix (e.g.
    `dl4j_tpu_serving_i0_`) so names line up with in-process
    kind-snapshots across the fleet.

    ONE instance per call: this returns a single instance's snapshot,
    so a text carrying samples from SEVERAL distinct `instance` labels
    (an aggregated scrape) must say which one to read — pass
    `instance=` to filter, otherwise the mix raises LOUDLY (silently
    last-wins counters and doubled histogram buckets are exactly the
    corruption kind-correct federation exists to prevent). Feed an
    aggregated scrape once per instance label, one FleetView.add each."""
    kinds = {}          # exposition name -> declared kind
    hist = {}           # name -> {"le": [(bound, cum)], "sum":, "count":}
    summ = {}           # name -> {"quantiles": {...}, "count":}
    out = {}
    seen_instances = set()

    def key(name):
        return name[len(strip_prefix):] \
            if strip_prefix and name.startswith(strip_prefix) else name

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        mt = _TYPE_RE.match(line)
        if mt:
            kinds[mt.group(1)] = mt.group(2)
            continue
        if line.startswith("#"):
            continue
        ms = _SAMPLE_RE.match(line)
        if ms is None:
            continue
        name, lbl, val = ms.group(1), _labels(ms.group(2)), ms.group(3)
        seen_instances.add(lbl.get("instance"))
        if instance is not None and \
                lbl.get("instance") != str(instance):
            continue
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    kinds.get(name[:-len(suffix)]) in ("histogram",
                                                       "summary"):
                base = name[:-len(suffix)]
                break
        kind = kinds.get(base)
        if kind == "histogram":
            h = hist.setdefault(base, {"le": [], "sum": 0.0, "count": 0})
            if name.endswith("_bucket"):
                h["le"].append((lbl.get("le"), _num(val)))
            elif name.endswith("_sum"):
                h["sum"] = float(val)
            elif name.endswith("_count"):
                h["count"] = _num(val)
        elif kind == "summary":
            s = summ.setdefault(base, {"quantiles": {}, "count": 0})
            if name.endswith("_count"):
                s["count"] = _num(val)
            elif "quantile" in lbl:
                s["quantiles"][lbl["quantile"]] = float(val)
        elif kind == "counter":
            out[key(base)] = {"kind": "counter", "value": _num(val)}
        elif kind == "gauge":
            out[key(base)] = {"kind": "gauge", "value": float(val)}
    if instance is None and len(seen_instances) > 1:
        raise ValueError(
            f"exposition carries samples from several instances "
            f"({sorted(str(i) for i in seen_instances)}): pass "
            f"instance= to pick one — parsing a mixed scrape as one "
            f"snapshot would last-win counters and double histogram "
            f"buckets")
    for base, h in hist.items():
        finite = [(float(le), cum) for le, cum in h["le"]
                  if le not in (None, "+Inf")]
        finite.sort()
        inf_cum = max((cum for le, cum in h["le"] if le == "+Inf"),
                      default=h["count"])
        counts, prev = [], 0
        for _, cum in finite:
            counts.append(cum - prev)
            prev = cum
        counts.append(inf_cum - prev)       # +Inf overflow entry
        out[key(base)] = {"kind": "histogram",
                          "buckets": [b for b, _ in finite],
                          "counts": counts, "sum": h["sum"],
                          "total": inf_cum}
    for base, s in summ.items():
        q = s["quantiles"]
        out[key(base)] = {"kind": "summary",
                          "p50": q.get("0.5"), "p99": q.get("0.99"),
                          "mean": None, "count": s["count"]}
    return out


def _as_kind_snapshot(source, strip_prefix=""):
    """Normalize one federation source: a kind-snapshot dict, a
    Prometheus text exposition, or any object exposing
    `kind_snapshot()` (ServingMetrics, MetricsRegistry)."""
    if isinstance(source, str):
        return parse_prometheus_text(source, strip_prefix=strip_prefix)
    if hasattr(source, "kind_snapshot"):
        return source.kind_snapshot()
    if isinstance(source, dict):
        return source
    raise TypeError(
        f"cannot federate {type(source).__name__}: need a kind-snapshot "
        f"dict, a Prometheus text exposition, or an object with "
        f"kind_snapshot() (ServingMetrics / MetricsRegistry)")


def _mean(vals):
    vals = [v for v in vals if v is not None]
    return (sum(vals) / len(vals)) if vals else None


# shed counters whose fleet total / per-instance share the federation
# report renders — the ONE canonical copy of serving/metrics.py's
# by-cause counter names on the fleet side (tools/fleet_report.py
# imports it; a new shed cause is added HERE and every fleet read-out
# follows)
SHED_KEYS = ("shed_queue_full", "shed_deadline", "shed_blocks",
             "shed_predicted", "shed_brownout")


class FleetView:
    """Merged view over N instances' kind-snapshots (module docstring:
    counters sum, gauges per-instance + min/mean/max, histograms
    bucket-wise, summaries per-instance only)."""

    def __init__(self, signal=None):
        self._instances = {}        # name -> kind snapshot (insertion
        #                             order = pid order in reports)
        self.signal = signal        # optional AutoscaleSignal whose
        #                             last decision snapshot() reports

    def add(self, name, source, strip_prefix=""):
        self._instances[str(name)] = _as_kind_snapshot(
            source, strip_prefix=strip_prefix)
        return self

    @property
    def instances(self):
        return list(self._instances)

    def _kind_of(self, name):
        kinds = {snap[name]["kind"] for snap in self._instances.values()
                 if name in snap}
        if len(kinds) > 1:
            raise ValueError(
                f"metric {name!r} has conflicting kinds across the "
                f"fleet: {sorted(kinds)} — same rename-fails-loudly "
                f"rule as the registry")
        return kinds.pop() if kinds else None

    # -- merged read-outs ---------------------------------------------
    def counters(self):
        """All counter-kind metrics summed across instances. Gauges
        and histograms NEVER land here — kind separation is the
        federation contract, not a convention."""
        out = {}
        names = {n for snap in self._instances.values() for n in snap}
        for name in sorted(names):
            if self._kind_of(name) != "counter":
                continue
            out[name] = sum(snap[name]["value"]
                            for snap in self._instances.values()
                            if name in snap)
        return out

    def counter(self, name, default=0):
        if self._kind_of(name) not in (None, "counter"):
            raise ValueError(f"metric {name!r} is not a counter")
        return sum((snap[name]["value"] or 0)
                   for snap in self._instances.values()
                   if name in snap) if self._kind_of(name) else default

    def gauge_view(self, name):
        """Per-instance gauge values + min/mean/max aggregate. None
        while no instance has set the gauge."""
        if self._kind_of(name) not in (None, "gauge"):
            raise ValueError(f"metric {name!r} is not a gauge")
        per = {inst: snap[name]["value"]
               for inst, snap in self._instances.items()
               if name in snap}
        vals = [v for v in per.values() if v is not None]
        return {"per_instance": per,
                "min": min(vals) if vals else None,
                "mean": _mean(vals),
                "max": max(vals) if vals else None}

    def gauge_sum(self, name):
        """Explicit derived read-out: the SUM of one gauge across
        instances (fleet capacity from per-replica service rates).
        Deliberately a separate verb from `gauge_view` — summing a
        gauge is a modeling decision the caller states, never a merge
        default."""
        vals = [v for v in self.gauge_view(name)["per_instance"]
                .values() if v is not None]
        return sum(vals) if vals else None

    def histogram(self, name):
        """Bucket-wise merged histogram state: (buckets, counts, sum,
        total). Grids must match exactly across instances (one name,
        one grid — the registry's first-registration rule, enforced
        across the fleet)."""
        if self._kind_of(name) not in (None, "histogram"):
            raise ValueError(f"metric {name!r} is not a histogram")
        buckets = None
        counts, total, s = None, 0, 0.0
        for inst, snap in self._instances.items():
            if name not in snap:
                continue
            h = snap[name]
            if buckets is None:
                buckets = list(h["buckets"])
                counts = [0] * len(h["counts"])
            elif list(h["buckets"]) != buckets:
                raise ValueError(
                    f"histogram {name!r} has mismatched bucket grids "
                    f"across the fleet ({inst}: {h['buckets']} vs "
                    f"{buckets}) — bucket-wise merge is only exact on "
                    f"one shared grid")
            counts = [a + b for a, b in zip(counts, h["counts"])]
            total += h["total"]
            s += h["sum"]
        if buckets is None:
            return None
        return {"buckets": buckets, "counts": counts, "sum": s,
                "total": total}

    def quantile(self, name, q):
        """Interpolated quantile of the MERGED histogram — equal to the
        pooled-sample histogram's quantile within bucket resolution
        (exactly equal to a histogram that observed every instance's
        samples, since the merged counts are its counts)."""
        h = self.histogram(name)
        if h is None:
            return None
        return bucket_quantile(h["buckets"], h["counts"], q)

    def shed_share(self):
        """Per-instance share of the fleet's total sheds (all causes) —
        the imbalance read-out: one replica absorbing most of the
        shedding is a router bug, not an autoscaling signal."""
        per = {}
        for inst, snap in self._instances.items():
            per[inst] = sum((snap[k]["value"] or 0) for k in SHED_KEYS
                            if k in snap
                            and snap[k]["kind"] == "counter")
        total = sum(per.values())
        return {inst: (n / total if total else 0.0)
                for inst, n in per.items()}

    def flat(self, name):
        """One instance's kind-snapshot flattened to the familiar
        snapshot() shape (counters/gauges by name, histograms and
        summaries as _p50/_p99/_mean/_count) — the per-instance table
        row and the obs_report metrics-section input."""
        snap = self._instances[name]
        out = {}
        for key, m in snap.items():
            if m["kind"] in ("counter", "gauge"):
                out[key] = m["value"]
            elif m["kind"] == "histogram":
                out[key + "_p50"] = bucket_quantile(
                    m["buckets"], m["counts"], 50)
                out[key + "_p99"] = bucket_quantile(
                    m["buckets"], m["counts"], 99)
                out[key + "_mean"] = (m["sum"] / m["total"]) \
                    if m["total"] else None
                out[key + "_count"] = m["total"]
            else:
                out[key + "_p50"] = m["p50"]
                out[key + "_p99"] = m["p99"]
                out[key + "_mean"] = m["mean"]
                out[key + "_count"] = m["count"]
        return out

    def snapshot(self):
        """The fleet read-out dict. ALWAYS-PRESENT keys (pinned in
        tests/test_obs.py, exposed on the federation report):
        `fleet_instances`, `fleet_slo_attainment`,
        `fleet_goodput_tokens_per_sec`, `autoscale_decision` — plus the
        merged inputs the autoscale detector consumes
        (`fleet_shed_predicted`, `fleet_service_rate_tokens_per_sec`,
        `fleet_occupancy_mean`). Every derived value is computed from
        the MERGED state (counters summed, gauges aggregated) — never
        re-sampled from a live instance, so a snapshot is a consistent
        artifact even while the fleet keeps serving."""
        counters = self.counters()
        out = {"fleet_instances": len(self._instances),
               "instances": self.instances}
        slo_total = counters.get("slo_total", 0)
        slo_met = counters.get("slo_met", 0)
        out["fleet_slo_attainment"] = (slo_met / slo_total
                                       if slo_total else None)
        # fleet capacity = sum of per-replica service-rate gauges (an
        # EXPLICIT derived read-out — see gauge_sum); goodput scales it
        # by the fleet-wide within-SLO token fraction
        rate = self.gauge_sum("service_rate_tokens_per_sec")
        out["fleet_service_rate_tokens_per_sec"] = rate
        toks = counters.get("tokens_out", 0)
        frac = (min(1.0, counters.get("slo_tokens_met", 0) / toks)
                if toks else None)
        out["fleet_goodput_tokens_per_sec"] = (
            rate * frac if rate is not None and frac is not None
            else None)
        out["fleet_tokens_out"] = toks
        out["fleet_shed_predicted"] = counters.get("shed_predicted", 0)
        out["fleet_sheds_total"] = sum(
            counters.get(k, 0) for k in SHED_KEYS)
        out["fleet_shed_share"] = self.shed_share()
        # fleet-control event counters (serving/fleet.py FleetManager):
        # summed like any counter (the manager's own metrics carry
        # them; a FleetManager.fleet_snapshot() overlays its live
        # values) — always present so tools/fleet_report.py renders
        # the control plane's activity next to the federation keys
        for key in ("replica_spawned", "replica_drained", "replica_dead",
                    "failover_resubmitted", "canary_rollbacks",
                    "wire_reconnects", "wire_retries",
                    "migrate_refused", "manager_epoch",
                    "replicas_adopted", "fenced_ops",
                    "journal_records", "requests_quarantined",
                    "breaker_open_total", "retry_budget_exhausted",
                    "degraded_mode_ticks", "infant_deaths",
                    "fused_windows", "decode_iterations",
                    "routed_affinity", "routed_spill",
                    "prefix_pull_hits", "prefix_pull_refused",
                    "prefix_pull_bytes"):
            out["fleet_" + key] = counters.get(key, 0)
        # fleet-wide dispatch amortization (fused decode windows): the
        # same ratio each instance derives, recomputed from the MERGED
        # counters so it weights instances by their dispatch volume
        disp = counters.get("dispatches", 0)
        out["fleet_iterations_per_dispatch"] = (
            counters.get("decode_iterations", 0) / disp
            if disp else None)
        # the breaker's live state is a GAUGE — federation can't sum
        # it; the manager's fleet_snapshot() overlays its own. Here the
        # per-instance max stands in (any open breaker reads open).
        states = [v for v in self.gauge_view(
            "breaker_state")["per_instance"].values() if v is not None]
        out["fleet_breaker_state"] = max(states) if states else 0.0
        # mean of per-instance occupancy statistics (summary kind:
        # recent scheduling-iteration slot occupancy) — the scale_down
        # input. A PARSED exposition carries no window mean (summaries
        # expose quantiles + count only), so the p50 stands in: for the
        # bounded [0,1] occupancy gate the median is an equally valid
        # idle read-out, and without the fallback a text-federated
        # fleet could never emit scale_down at all.
        occ = _mean([
            snap["occupancy"]["mean"]
            if snap["occupancy"].get("mean") is not None
            else snap["occupancy"].get("p50")
            for snap in self._instances.values()
            if snap.get("occupancy", {}).get("kind") == "summary"])
        out["fleet_occupancy_mean"] = occ
        out["autoscale_decision"] = (self.signal.decision
                                     if self.signal is not None
                                     else None)
        return out


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------
def _trace_meta(trace, name):
    for e in trace.get("traceEvents", ()):
        if e.get("ph") == "M" and e.get("name") == name:
            return e.get("args") or {}
    return {}


def merge_traces(traces, names=None):
    """Stitch N Chrome traces into ONE Perfetto-loadable trace.

    Alignment: each trace's `clock_sync` anchor
    (`wallclock_ns_at_ts0`, PR 7) maps its ts=0 onto the wall clock;
    every trace's events shift onto the EARLIEST anchor's timeline
    (shift_us = (anchor_i - min_anchor) / 1e3). Within one process the
    wall and monotonic clocks tick together (pinned), so cross-instance
    span ORDER in the merged file is the real order. A trace with no
    anchor merges unshifted (its spans still render, on its own ts
    base — degraded, not dropped).

    Separation: trace i becomes process group pid=i+1 with its own
    `process_name` metadata (from `names`, else the trace's
    process_name / clock_sync instance metadata, else `instance<i>`)
    and its thread_name lanes preserved — so a migrated request's
    `req-<id>` lane appears once per instance, tied together by the
    shared trace id in its spans' args."""
    anchors, labels = [], []
    for i, t in enumerate(traces):
        sync = _trace_meta(t, "clock_sync")
        anchors.append(sync.get("wallclock_ns_at_ts0"))
        if names is not None and i < len(names):
            labels.append(str(names[i]))
        else:
            labels.append(
                sync.get("instance")
                or _trace_meta(t, "process_name").get("name")
                or f"instance{i}")
    known = [a for a in anchors if a is not None]
    base = min(known) if known else None
    events = []
    if base is not None:
        events.append({"ph": "M", "pid": 0, "tid": 0,
                       "name": "clock_sync",
                       "args": {"wallclock_ns_at_ts0": base,
                                "merged_instances": labels}})
    for i, t in enumerate(traces):
        pid = i + 1
        shift_us = ((anchors[i] - base) / 1e3
                    if anchors[i] is not None and base is not None
                    else 0.0)
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": labels[i]}})
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": i}})
        for e in t.get("traceEvents", ()):
            if e.get("ph") == "M":
                # per-trace process_name/clock_sync already rewritten
                # above; thread_name lanes carry over under the new pid
                if e.get("name") in ("process_name", "clock_sync",
                                     "process_sort_index"):
                    continue
                ne = dict(e)
                ne["pid"] = pid
                events.append(ne)
                continue
            ne = dict(e)
            ne["pid"] = pid
            if "ts" in ne:
                ne["ts"] = ne["ts"] + shift_us
            events.append(ne)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# autoscaling signal
# ---------------------------------------------------------------------------
class AutoscaleSignal:
    """Windowed, hysteresis-bounded scale decision over fleet
    snapshots (module docstring has the decision table).

    Feed `observe()` one fleet snapshot per observation window (the
    load_sweep fleet driver observes once per schedule slice); it
    returns the CURRENT decision. Inputs per observation:

      * `fleet_shed_predicted` — the merged CUMULATIVE shed counter
        (any monotone shed counter works; predicted-miss sheds are the
        ROADMAP's chosen leading indicator because they fire at
        enqueue, before goodput is lost);
      * `fleet_service_rate_tokens_per_sec` — the fleet capacity
        estimate (sum of per-replica admission-estimator gauges);
      * `fleet_occupancy_mean` — mean recent slot occupancy (the
        scale_down input; None disables scale_down).

    Mechanics: over the last `window` observations, sheds-per-window
    deltas are split into early/late halves. Sheds are ACCRUING when
    the late-half MEDIAN delta >= `min_shed_rate` (a cumulative
    counter actively rising — steady-state overload counts; the
    recipe's "shed rising" is about the counter, not its second
    derivative). The median, not the mean: one anomalous burst window
    lingers in the delta window for half its length and a mean would
    keep the raw verdict flipped that whole time — the same
    outlier-rejection argument the admission estimator's median makes
    against compile spikes. Service rate is RISING when the late-half
    mean exceeds the early-half mean by more than `flat_tol`
    (relative); FLAT when within +/- `flat_tol`. On top of that,
    decisions change only after `hysteresis` consecutive identical
    raw verdicts. Deterministic: no clock reads, no randomness — the
    same observation sequence always yields the same decision
    sequence."""

    SCALE_UP = "scale_up"
    SCALE_DOWN = "scale_down"
    HOLD = "hold"

    def __init__(self, window=6, min_shed_rate=1.0, flat_tol=0.25,
                 low_occupancy=0.25, hysteresis=2):
        if window < 4:
            raise ValueError(f"window must be >= 4 (two halves of "
                             f"deltas), got {window}")
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got "
                             f"{hysteresis}")
        self.window = int(window)
        self.min_shed_rate = float(min_shed_rate)
        self.flat_tol = float(flat_tol)
        self.low_occupancy = float(low_occupancy)
        self.hysteresis = int(hysteresis)
        self._obs = collections.deque(maxlen=self.window)
        self._pending = self.HOLD
        self._pending_n = 0
        self.decision = self.HOLD
        self.transitions = []       # (observation index, decision)
        self._n_obs = 0

    # -- inputs --------------------------------------------------------
    def observe(self, snapshot=None, *, sheds=None, service_rate=None,
                occupancy=None):
        """One observation window: pass a `FleetView.snapshot()` dict
        or the three inputs explicitly. Returns the current
        (hysteresis-bounded) decision."""
        if snapshot is not None:
            sheds = snapshot.get("fleet_shed_predicted", 0) \
                if sheds is None else sheds
            if service_rate is None:
                service_rate = snapshot.get(
                    "fleet_service_rate_tokens_per_sec")
            if occupancy is None:
                occupancy = snapshot.get("fleet_occupancy_mean")
        self._n_obs += 1
        self._obs.append((float(sheds or 0), float(service_rate or 0.0),
                          None if occupancy is None
                          else float(occupancy)))
        raw = self._raw()
        if raw == self._pending:
            self._pending_n += 1
        else:
            self._pending, self._pending_n = raw, 1
        if raw != self.decision and self._pending_n >= self.hysteresis:
            self.decision = raw
            self.transitions.append((self._n_obs, raw))
        return self.decision

    def reset(self):
        """Forget the observation window and re-enter warm-up (decision
        back to HOLD; transition history kept). The fleet manager calls
        this after ACTING on a decision — the actuation twin of the
        hysteresis bound: one action per argued regime, so the next
        scale move must be argued entirely from observations of the NEW
        fleet shape instead of the stale window that justified the
        last one (without it a sustained-overload window would spawn a
        replica per tick)."""
        self._obs.clear()
        self._pending, self._pending_n = self.HOLD, 0
        self.decision = self.HOLD

    # -- classification ------------------------------------------------
    def _raw(self):
        if len(self._obs) < self.window:
            return self.HOLD        # warm-up: never act on a part-window
        sheds = [o[0] for o in self._obs]
        # cumulative counter deltas; a counter reset (restarted
        # instance) reads as a one-window zero, not a negative spike
        deltas = [max(0.0, b - a) for a, b in zip(sheds, sheds[1:])]
        h = len(deltas) // 2
        late = sorted(deltas[h:])
        # LOWER median (even-length halves round down): a lone burst
        # window can never be the statistic, whatever the window size
        late_median = late[(len(late) - 1) // 2] if late else 0.0
        shed_active = late_median >= self.min_shed_rate
        rates = [o[1] for o in self._obs]
        rh = len(rates) // 2
        r_early = _mean(rates[:rh]) or 0.0
        r_late = _mean(rates[rh:]) or 0.0
        denom = max(abs(r_early), abs(r_late), 1e-9)
        rel = (r_late - r_early) / denom
        service_rising = rel > self.flat_tol
        service_flat = abs(rel) <= self.flat_tol
        occs = [o[2] for o in self._obs if o[2] is not None]
        occ = _mean(occs)
        if shed_active:
            # rising service = the fleet is still ramping into its
            # capacity (queue transient — adding replicas would chase
            # it); flat OR sagging service under sheds = capacity
            return self.HOLD if service_rising else self.SCALE_UP
        if (sum(deltas[h:]) == 0.0 and service_flat
                and occ is not None and occ < self.low_occupancy):
            return self.SCALE_DOWN      # idle capacity, no pressure
        return self.HOLD
