"""Unified observability layer: span tracing + metrics registry.

The repo's telemetry before this package was `ServingMetrics` snapshots
and ad-hoc prints; the ROADMAP's traffic-harness and canary-fleet items
both presuppose per-request timelines and SLO attainment counters. This
package is that substrate:

  * `trace.Tracer` — monotonic-clock spans in a lock-light bounded ring,
    exported as Chrome trace-event JSON (Perfetto / chrome://tracing).
    Threaded through both servers (enqueue -> queue wait -> batch
    formation -> dispatch -> complete, one span per decode iteration)
    and the training fit loops (staging, dispatch, health, checkpoint).
  * `registry.MetricsRegistry` — the named counter/gauge/reservoir/
    histogram surface everything publishes through (serving metrics,
    PS-transport retries, async-iterator queue depth, training-health
    counters), exported as a Prometheus text route on `ui/server.py`
    (`/metrics`). `Histogram` is the fixed-bucket cumulative kind the
    serving SLO metrics (TTFT, inter-token) scrape as.
  * `trace.FlightRecorder` — arm the tracer when rolling p99 crosses a
    threshold, so SLO violations self-document.
  * `decompose.decompose` — post-hoc span-derived latency
    decomposition: each served request's total attributed to
    queue-wait / prefill / decode / scheduling-gap phases (the
    traffic-harness analyzer; rendered by `tools/obs_report.py`).
  * `fleet.FleetView` / `fleet.merge_traces` /
    `fleet.AutoscaleSignal` — the fleet plane: kind-correct metrics
    federation over N instances (in-process or parsed `/metrics`
    text), clock-anchor trace stitching into one Perfetto file with
    per-instance process groups, and the ROADMAP autoscaling recipe
    as a windowed, hysteresis-bounded, tested detector (rendered by
    `tools/fleet_report.py`).

Hard constraints: stdlib-only (importing or using obs can never pull in
jax or add a device dispatch — pinned by test), and the disabled tracer
costs nanoseconds per call site (pinned by test). `TRACER` is the
process-wide default tracer (disabled until `enable_tracing()`);
`registry.default_registry()` is the process-wide metrics surface.
"""
from __future__ import annotations

from . import registry
from .decompose import decompose, decompose_requests
from .fleet import (AutoscaleSignal, FleetView, merge_traces,
                    parse_prometheus_text)
from .registry import Histogram, MetricsRegistry, default_registry, fmt
from .trace import FlightRecorder, Span, TraceContext, Tracer

TRACER = Tracer(enabled=False)


def get_tracer():
    """The process-wide tracer (servers and fit loops default to it)."""
    return TRACER


def span(name, **kw):
    """Record a span on the global tracer (no-op while disabled)."""
    return TRACER.span(name, **kw)


def enable_tracing():
    """Turn the global tracer on; returns it (for .save()/.spans())."""
    return TRACER.enable()


def disable_tracing():
    return TRACER.disable()


__all__ = [
    "Tracer", "Span", "TraceContext", "FlightRecorder",
    "MetricsRegistry", "Histogram",
    "default_registry", "fmt", "registry",
    "decompose", "decompose_requests",
    "FleetView", "AutoscaleSignal", "merge_traces",
    "parse_prometheus_text",
    "TRACER", "get_tracer", "span", "enable_tracing", "disable_tracing",
]
