"""Span-derived latency decomposition: where did each request's time go?

The PR 6 tracer already records the full serving timeline — a
`serve.request` span per request on its own `req-<id>` lane, its
`serve.queue_wait` (closed at batch formation / slot admission), the
`decode.prefill` dispatch tagged with the request's trace id, and the
shared server-lane dispatch windows (`decode.dispatch` /
`decode.verify` / `serve.dispatch`). This module walks those lanes
post-hoc and attributes each completed request's total latency to four
phases:

  * `queue_wait_ms` — submit -> admission (the `serve.queue_wait` span;
    queueing pressure, the thing arrival rate controls);
  * `prefill_ms`    — the request's OWN prompt prefill (zero for the
    micro-batch server, which has no prefill phase);
  * `decode_ms`     — time inside device-dispatch windows overlapping
    the request's active window (admission -> completion);
  * `sched_gap_ms`  — the remainder: host scheduling, batch formation,
    and OTHER requests' prefills stalling this request's decode. A fat
    sched_gap under load is exactly the head-of-line signal the
    chunked-prefill round exists to attack.

The server lane is single-threaded, so its spans never overlap each
other: after clipping every term to the request's active window the four
phases partition the total (fractions sum to 1, up to clock jitter).

Input is anything `tools/obs_report.py` accepts — a live `Tracer`, a
list of `Span` tuples (e.g. a flight-recorder capture), or a saved
Chrome trace dict. Stdlib-only like the rest of obs/: the analyzer runs
post-hoc on host data and can never add a device dispatch.
"""
from __future__ import annotations

from .registry import fmt, percentile

__all__ = ["decompose", "decompose_requests"]

# server-lane spans that represent a device dispatch in flight (prefill
# is named separately so it can be attributed as its own phase)
_BUSY_NAMES = ("decode.dispatch", "decode.verify", "serve.dispatch")
_PHASES = ("queue_wait_ms", "prefill_ms", "decode_ms", "sched_gap_ms")


def _normalize(spans_or_trace):
    """-> list of {name, t0, dur, trace_id, pid} dicts in MILLISECONDS
    on one consistent clock (monotonic for live spans, rebased for a
    saved Chrome trace — decomposition only ever subtracts timestamps
    from the same source, so the two bases never mix). `pid` carries
    the trace's process group (0 for live spans / single traces): a
    MERGED multi-instance trace (obs.fleet.merge_traces) has one
    single-threaded server lane PER instance, so busy windows and
    request lanes must attribute within their own pid — pooling them
    would charge every request with the other replicas' concurrent
    dispatch windows."""
    if spans_or_trace is None:
        return []
    if hasattr(spans_or_trace, "spans"):        # Tracer
        spans_or_trace = spans_or_trace.spans()
    out = []
    if isinstance(spans_or_trace, dict):        # chrome trace JSON
        for e in spans_or_trace.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            args = e.get("args") or {}
            out.append({"name": e.get("name"),
                        "t0": e.get("ts", 0) / 1e3,
                        "dur": e.get("dur", 0) / 1e3,
                        "trace_id": args.get("trace_id"),
                        "pid": e.get("pid", 0)})
    else:
        for s in spans_or_trace:                # Span namedtuples
            out.append({"name": s.name, "t0": s.t0_ns / 1e6,
                        "dur": s.dur_ns / 1e6, "trace_id": s.trace_id,
                        "pid": 0})
    return out


def _overlap(a0, a1, b0, b1):
    return max(0.0, min(a1, b1) - max(a0, b0))


def decompose_requests(spans_or_trace):
    """Per-request phase attribution: one row per `serve.request` span,
    sorted by request start time. Rows carry the four phase durations
    plus `total_ms`; phases are clipped to the request's window so they
    partition the total."""
    evs = _normalize(spans_or_trace)
    # keyed by (pid, trace_id): a merged fleet trace carries one
    # single-threaded server lane PER process group, and a MIGRATED
    # request's trace id legitimately appears on two pids (one
    # serve.request per instance that served it) — each attributes
    # against its OWN instance's busy windows only
    reqs, queues, prefills, busy = {}, {}, {}, {}
    for e in evs:
        key = (e["pid"], e["trace_id"])
        if e["name"] == "serve.request" and e["trace_id"] is not None:
            reqs[key] = e
        elif e["name"] == "serve.queue_wait" and \
                e["trace_id"] is not None:
            queues[key] = e
        elif e["name"] == "decode.prefill":
            prefills.setdefault(key, []).append(e)
        elif e["name"] in _BUSY_NAMES:
            busy.setdefault(e["pid"], []).append(
                (e["t0"], e["t0"] + e["dur"]))
    for windows in busy.values():
        windows.sort()
    rows = []
    for (pid, tid), req in sorted(reqs.items(),
                                  key=lambda kv: kv[1]["t0"]):
        total = req["dur"]
        t0, t1 = req["t0"], req["t0"] + total
        qw = min(queues[(pid, tid)]["dur"], total) \
            if (pid, tid) in queues else 0.0
        win0 = t0 + qw          # active window: admission -> completion
        pf = sum(_overlap(p["t0"], p["t0"] + p["dur"], win0, t1)
                 for p in prefills.get((pid, tid), ()))
        dec = sum(_overlap(b0, b1, win0, t1)
                  for b0, b1 in busy.get(pid, ()))
        gap = max(0.0, total - qw - pf - dec)
        rows.append({"trace_id": tid, "total_ms": total,
                     "queue_wait_ms": qw, "prefill_ms": pf,
                     "decode_ms": dec, "sched_gap_ms": gap})
    return rows


def decompose(spans_or_trace):
    """Aggregate decomposition: per-phase total/mean/p50/p99 over every
    completed request plus each phase's fraction of total request time.
    The shape `tools/obs_report.py` renders and `tools/load_sweep.py`
    ships in its combined report."""
    rows = decompose_requests(spans_or_trace)
    out = {"n_requests": len(rows), "phases": {}, "fractions": {},
           "requests": rows}
    if not rows:
        return out
    grand = sum(r["total_ms"] for r in rows) or 1e-12
    for ph in _PHASES + ("total_ms",):
        vals = sorted(r[ph] for r in rows)
        tot = sum(vals)
        out["phases"][ph] = {
            "total_ms": fmt(tot), "mean_ms": fmt(tot / len(vals)),
            "p50_ms": fmt(percentile(vals, 50)),
            "p99_ms": fmt(percentile(vals, 99))}
        if ph != "total_ms":
            out["fractions"][ph] = fmt(tot / grand, 4)
    return out
