"""Span tracer: monotonic-clock spans in a lock-light bounded ring.

Dapper-style (Sigelman et al., 2010) host-side tracing for both servers
and the training fit loops: a span is (name, category, track, trace_id,
start_ns, duration_ns, args), timed with `time.monotonic_ns()` and
appended to a bounded `collections.deque` — CPython deque appends are
atomic under the GIL, so the hot path takes NO lock and old spans fall
off the far end instead of growing memory. Spans export as Chrome
trace-event JSON (`chrome_trace()` / `save()`) that loads directly in
Perfetto or chrome://tracing; nesting comes from time containment on a
track, so a request's `queue_wait` span draws inside its `request` span.

Contracts (pinned by tests/test_obs.py):

  * Disabled is free. `span()`/`emit()` on a disabled tracer is a single
    attribute check returning a shared no-op — nanosecond-scale, no
    allocation, no clock read. Serving and training ship with tracing
    OFF and pay nothing.
  * Zero device work. This module (the whole obs/ package) never imports
    jax or numpy: recording a span can never add a device dispatch. The
    only device interaction is the OPTIONAL flight-recorder seam, which
    takes `optimize.profiler.trace` as an injected callable.

Tracks map to Chrome trace "threads": give request-scoped spans
`track=f"req-{id}"` and scheduler spans `track="server"` so concurrent
requests render as parallel lanes instead of false nesting.

`FlightRecorder` makes SLO violations self-document: feed it request
latencies, and when the rolling p99 crosses the threshold it arms the
tracer for the next N spans (and optionally starts a jax.profiler device
trace through the injected seam), storing the capture for post-mortem.
"""
from __future__ import annotations

import collections
import datetime
import json
import threading
import time

monotonic_ns = time.monotonic_ns

Span = collections.namedtuple(
    "Span", ["name", "cat", "track", "trace_id", "t0_ns", "dur_ns", "args"])


class TraceContext(collections.namedtuple(
        "TraceContext", ["trace_id", "parent_span", "origin"])):
    """The cross-process trace baton (Dapper's propagated context):
    `trace_id` identifies the request across every process that ever
    served it, `parent_span` names the lane (`req-<id>`) the
    continuation should extend, `origin` names the instance that
    emitted the context. It rides the `RequestArtifact` manifest
    through preempt/migrate (serving/kvstate.py) as a plain dict —
    `to_manifest()`/`from_manifest()` — so the wire format stays
    JSON and the destination server can continue the request's lane
    under the SAME trace id, making a migrated request read as one
    timeline after `obs.fleet.merge_traces`."""

    __slots__ = ()

    def to_manifest(self):
        return {"trace_id": self.trace_id,
                "parent_span": self.parent_span,
                "origin": self.origin}

    @classmethod
    def from_manifest(cls, d):
        """None-tolerant: artifacts written before trace propagation
        (or by a producer that never traced) read as no context."""
        if not d or d.get("trace_id") is None:
            return None
        return cls(d.get("trace_id"), d.get("parent_span"),
                   d.get("origin"))


class _Noop:
    """Shared do-nothing context manager: the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_cat", "_track", "_trace_id",
                 "_args", "_t0")

    def __init__(self, tracer, name, cat, track, trace_id, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._track = track
        self._trace_id = trace_id
        self._args = args

    def __enter__(self):
        self._t0 = monotonic_ns()
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        self._tracer.emit(self._name, t0, monotonic_ns() - t0,
                          cat=self._cat, track=self._track,
                          trace_id=self._trace_id, args=self._args)
        return False


class Tracer:
    """Bounded span recorder; disabled by default."""

    def __init__(self, capacity=16384, enabled=False, instance=None):
        self._buf = collections.deque(maxlen=int(capacity))
        self._enabled = bool(enabled)
        # instance name: the default process_name of this tracer's
        # chrome_trace() export. A fleet names its replicas' tracers so
        # obs.fleet.merge_traces renders each as its own process group.
        self.instance = None if instance is None else str(instance)
        self._auto = None        # [remaining, restore_enabled, callback]
        self._lock = threading.Lock()    # export/clear only, never emit
        # wallclock anchor: ONE (wall_ns, monotonic_ns) pair captured at
        # construction. Span timestamps stay monotonic (immune to NTP
        # steps); the anchor lets chrome_trace() emit a `clock_sync`
        # metadata event so two saved traces — different runs, different
        # processes — can be aligned on the wall clock in Perfetto.
        self._wall_anchor = (time.time_ns(), monotonic_ns())

    @property
    def enabled(self):
        return self._enabled

    def enable(self):
        self._enabled = True
        return self

    def disable(self):
        self._enabled = False
        return self

    def enable_for(self, n_spans, on_done=None, restore=None):
        """Flight-recorder arm: record the next `n_spans` spans, then
        restore the previous enabled state (or the explicit `restore`
        value) and call `on_done()`. A tracer that was already enabled
        stays enabled afterwards."""
        self._auto = [int(n_spans),
                      self._enabled if restore is None else bool(restore),
                      on_done]
        self._enabled = True
        return self

    # -- hot path ------------------------------------------------------
    def span(self, name, cat="host", track=None, trace_id=None, **args):
        """Context manager timing one span. Disabled: returns a shared
        no-op without reading the clock or allocating."""
        if not self._enabled:
            return _NOOP
        return _SpanCtx(self, name, cat, track, trace_id, args or None)

    def emit(self, name, t0_ns, dur_ns, cat="host", track=None,
             trace_id=None, args=None):
        """Record one completed span with explicit timing — for spans
        whose start was a plain timestamp taken before the outcome was
        known (queue wait: t_submit -> batch formation)."""
        if not self._enabled:
            return
        self._buf.append(Span(name, cat, track, trace_id,
                              int(t0_ns), int(dur_ns), args))
        if self._auto is not None:
            self._tick_auto()

    def _tick_auto(self):
        """Flight-recorder countdown. Only runs while a capture is armed
        (the steady-state emit path never takes a lock); the lock makes
        the decrement atomic so concurrent emitters can neither strand
        the capture (lost decrement -> tracer enabled forever) nor fire
        the completion callback twice. The callback runs OUTSIDE the
        lock — it reads the span buffer through spans(), which takes it."""
        with self._lock:
            auto = self._auto
            if auto is None:        # another emitter already completed it
                return
            auto[0] -= 1
            if auto[0] > 0:
                return
            self._auto = None
            self._enabled = auto[1]
            cb = auto[2]
        if cb is not None:
            cb()

    def instant(self, name, cat="host", track=None, **args):
        """Zero-duration marker (flight-recorder trigger, swap installed,
        rollback landed)."""
        if not self._enabled:
            return
        self._buf.append(Span(name, cat, track, None,
                              monotonic_ns(), 0, args or None))

    # -- read-out ------------------------------------------------------
    def spans(self, name=None):
        with self._lock:
            out = list(self._buf)
        return out if name is None else [s for s in out if s.name == name]

    def clear(self):
        with self._lock:
            self._buf.clear()

    def __len__(self):
        return len(self._buf)

    def chrome_trace(self, process_name=None, pid=0):
        """Chrome trace-event JSON (loads in Perfetto / chrome://tracing):
        one complete ("ph":"X") event per span, ts/dur in microseconds
        rebased to the earliest span, tracks mapped to tids with
        thread_name metadata so lanes are labeled. Every event carries
        an EXPLICIT `pid` (default 0, schema-compatible with every
        existing consumer) and the `process_name` metadata defaults to
        the tracer's `instance` name when one was set — so a
        multi-server merge (`obs.fleet.merge_traces`) renders each
        instance as its own labeled process group in Perfetto.

        A `clock_sync` metadata event anchors ts=0 to the wall clock
        (`wallclock_ns_at_ts0`): spans are timed on the bare monotonic
        clock, whose zero is arbitrary per boot/process, so WITHOUT the
        anchor two saved traces cannot be aligned. To overlay trace B on
        trace A in Perfetto, shift B's events by
        (B.wallclock_ns_at_ts0 - A.wallclock_ns_at_ts0) / 1e3 us —
        exactly what merge_traces does."""
        if process_name is None:
            process_name = self.instance or "deeplearning4j_tpu"
        pid = int(pid)
        spans = self.spans()
        wall_ns, mono_ns = self._wall_anchor
        base = min((s.t0_ns for s in spans), default=mono_ns)
        tracks = {}
        for s in spans:
            tracks.setdefault(s.track or "main", len(tracks))
        wall_at_base = wall_ns + (base - mono_ns)
        sync_args = {
            "wallclock_ns_at_ts0": wall_at_base,
            "monotonic_ns_at_ts0": base,
            "wallclock_iso": datetime.datetime.fromtimestamp(
                wall_at_base / 1e9,
                datetime.timezone.utc).isoformat()}
        if self.instance is not None:
            sync_args["instance"] = self.instance
        events = [{"ph": "M", "pid": pid, "tid": 0,
                   "name": "process_name",
                   "args": {"name": process_name}},
                  {"ph": "M", "pid": pid, "tid": 0, "name": "clock_sync",
                   "args": sync_args}]
        for track, tid in tracks.items():
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": track}})
        for s in spans:
            args = dict(s.args) if s.args else {}
            if s.trace_id is not None:
                args["trace_id"] = s.trace_id
            events.append({
                "name": s.name, "cat": s.cat, "ph": "X",
                "ts": (s.t0_ns - base) / 1e3, "dur": s.dur_ns / 1e3,
                "pid": pid, "tid": tracks[s.track or "main"],
                "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path, process_name=None, pid=0):
        """Write the Chrome trace JSON to `path` (open in Perfetto)."""
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(process_name, pid=pid), fh)
        return path


class FlightRecorder:
    """Capture-on-SLO-violation: arm the tracer when rolling p99 degrades.

    Feed request latencies via `observe(latency_ms)` (the serving loops
    do this on every completion when a recorder is attached). Over a
    rolling window of `window` samples, once at least `min_samples` have
    arrived and the window p99 crosses `threshold_ms`, the recorder:

      1. marks the trigger (`tracer.instant("flight.trigger")`),
      2. arms the tracer for the next `capture_spans` spans
         (`enable_for` — a tracer that was already on stays on), and
      3. optionally starts a device trace through `device_tracer`, a
         `contextmanager(logdir)` callable — pass
         `optimize.profiler.trace` to capture a jax.profiler window; the
         obs package itself never imports jax.

    When the capture completes, the spans are snapshotted into
    `captures` (bounded) so the violation self-documents even if the
    ring has since wrapped. `cooldown_s` rate-limits re-triggering."""

    def __init__(self, tracer, threshold_ms, window=256, min_samples=32,
                 capture_spans=512, cooldown_s=30.0, max_captures=8,
                 device_tracer=None, device_trace_dir=None):
        self.tracer = tracer
        self.threshold_ms = float(threshold_ms)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.capture_spans = int(capture_spans)
        self.cooldown_s = float(cooldown_s)
        self.device_tracer = device_tracer
        self.device_trace_dir = device_trace_dir
        self._lat = collections.deque(maxlen=self.window)
        self._above = 0     # samples in the window at/over the threshold
        self._lock = threading.Lock()
        self._capturing = False
        self._last_trigger = None
        self._device_ctx = None
        self.captures = collections.deque(maxlen=int(max_captures))
        self.triggers = 0

    def rolling_p99(self):
        from .registry import percentile
        with self._lock:
            vals = sorted(self._lat)
        return percentile(vals, 99)

    def observe(self, latency_ms):
        """Record one request latency; trigger a capture when the rolling
        p99 crosses the threshold. O(1) except on the (rare) trigger."""
        from .registry import percentile
        with self._lock:
            latency_ms = float(latency_ms)
            # O(1) count of over-threshold samples currently in the
            # window (the deque evicts silently, so track the evictee
            # ourselves). The p99 sort only runs while at least one such
            # sample is in the window — and a violation that arrived
            # earlier keeps arming the check until it ages out, so
            # fast-requests-after-a-spike can still trigger (the spike
            # IS the p99).
            if len(self._lat) == self._lat.maxlen and \
                    self._lat[0] >= self.threshold_ms:
                self._above -= 1
            self._lat.append(latency_ms)
            if latency_ms >= self.threshold_ms:
                self._above += 1
            if (self._capturing
                    or len(self._lat) < self.min_samples
                    or self._above == 0):
                return
            now = time.monotonic()
            if (self._last_trigger is not None
                    and now - self._last_trigger < self.cooldown_s):
                return
            p99 = percentile(sorted(self._lat), 99)
            if p99 < self.threshold_ms:
                return
            self._capturing = True
            self._last_trigger = now
            self.triggers += 1
        self._trigger(p99)

    def _trigger(self, p99):
        if self.device_tracer is not None and \
                self.device_trace_dir is not None:
            try:
                self._device_ctx = self.device_tracer(
                    self.device_trace_dir)
                self._device_ctx.__enter__()
            except Exception:       # device trace is best-effort
                self._device_ctx = None
        # remember the PRE-trigger state before enabling for the marker:
        # a tracer the recorder itself turned on must turn back off when
        # the capture completes
        prev = self.tracer.enabled
        self.tracer.enable()        # marker must land in the ring
        self.tracer.instant("flight.trigger", cat="flight",
                            p99_ms=round(p99, 3),
                            threshold_ms=self.threshold_ms)
        self.tracer.enable_for(self.capture_spans, on_done=self._on_done,
                               restore=prev)

    def _on_done(self):
        if self._device_ctx is not None:
            try:
                self._device_ctx.__exit__(None, None, None)
            except Exception:
                pass
            self._device_ctx = None
        spans = self.tracer.spans()[-(self.capture_spans + 1):]
        p99 = self.rolling_p99()
        with self._lock:
            self.captures.append({
                "p99_ms": p99,
                "threshold_ms": self.threshold_ms,
                "spans": spans,
                "device_trace_dir": (self.device_trace_dir
                                     if self.device_tracer else None)})
            self._capturing = False
