"""KMeans clustering.

TPU-native equivalent of reference deeplearning4j-core clustering/kmeans/
(KMeansClustering + cluster/ strategy classes): kmeans++ initialization on
the host, then jitted Lloyd iterations — the [N,K] distance matrix is one
MXU matmul per iteration.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=(2,))
def _lloyd_step(x, centers, K):
    """One assignment+update step. x [N,D], centers [K,D]."""
    d2 = (jnp.sum(x * x, axis=1)[:, None]
          - 2.0 * x @ centers.T
          + jnp.sum(centers * centers, axis=1)[None, :])
    assign = jnp.argmin(d2, axis=1)                     # [N]
    one_hot = jax.nn.one_hot(assign, K, dtype=x.dtype)  # [N,K]
    counts = jnp.sum(one_hot, axis=0)                   # [K]
    sums = one_hot.T @ x                                # [K,D]
    new_centers = jnp.where(counts[:, None] > 0,
                            sums / jnp.maximum(counts, 1.0)[:, None],
                            centers)
    cost = jnp.sum(jnp.min(d2, axis=1))
    return new_centers, assign, cost


class KMeansClustering:
    """reference: clustering/kmeans/KMeansClustering.java"""

    def __init__(self, k, max_iterations=100, tol=1e-6, seed=12345):
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.tol = float(tol)
        self.seed = int(seed)
        self.centers = None
        self.cost = None

    @staticmethod
    def setup(k, max_iterations=100, seed=12345):
        return KMeansClustering(k, max_iterations, seed=seed)

    def _init_pp(self, x, rng):
        """kmeans++ seeding."""
        n = x.shape[0]
        centers = [x[rng.integers(0, n)]]
        for _ in range(1, self.k):
            d2 = np.min(
                [np.sum((x - c) ** 2, axis=1) for c in centers], axis=0)
            p = d2 / max(d2.sum(), 1e-12)
            centers.append(x[rng.choice(n, p=p)])
        return np.stack(centers)

    def fit(self, points):
        x = np.asarray(points, np.float32)
        rng = np.random.default_rng(self.seed)
        centers = jnp.asarray(self._init_pp(x, rng))
        xd = jnp.asarray(x)
        prev_cost = None
        assign = None
        for _ in range(self.max_iterations):
            centers, assign, cost = _lloyd_step(xd, centers, self.k)
            cost = float(cost)
            if prev_cost is not None and abs(prev_cost - cost) < self.tol:
                break
            prev_cost = cost
        self.centers = np.asarray(centers)
        self.cost = prev_cost
        self.labels = np.asarray(assign)
        return self

    applyTo = fit

    def predict(self, points):
        x = np.asarray(points, np.float32)
        d2 = ((x[:, None, :] - self.centers[None, :, :]) ** 2).sum(-1)
        return np.argmin(d2, axis=1)
