from .kmeans import KMeansClustering
from .trees import KDTree, VPTree

__all__ = ["KDTree", "KMeansClustering", "VPTree"]
