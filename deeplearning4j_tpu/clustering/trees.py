"""Spatial trees for nearest-neighbor search: VPTree, KDTree.

TPU-native equivalent of reference deeplearning4j-core clustering/vptree/
(VPTree.java — vantage-point tree used by wordsNearest and Barnes-Hut
t-SNE neighbor search) and clustering/kdtree/KDTree.java.
"""
from __future__ import annotations

import heapq

import numpy as np


class VPTree:
    """Vantage-point tree over euclidean distance.
    reference: clustering/vptree/VPTree.java."""

    class _Node:
        __slots__ = ("index", "threshold", "inside", "outside")

        def __init__(self, index):
            self.index = index
            self.threshold = 0.0
            self.inside = None
            self.outside = None

    def __init__(self, points, seed=123):
        self.points = np.asarray(points, np.float64)
        self._rng = np.random.default_rng(seed)
        idx = list(range(len(self.points)))
        self.root = self._build(idx)

    def _dist(self, i, j):
        return float(np.linalg.norm(self.points[i] - self.points[j]))

    def _build(self, idx):
        if not idx:
            return None
        vp = idx[self._rng.integers(0, len(idx))]
        rest = [i for i in idx if i != vp]
        node = VPTree._Node(vp)
        if not rest:
            return node
        dists = np.linalg.norm(self.points[rest] - self.points[vp], axis=1)
        median = float(np.median(dists))
        node.threshold = median
        inside = [i for i, d in zip(rest, dists) if d <= median]
        outside = [i for i, d in zip(rest, dists) if d > median]
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def knn(self, query, k):
        """k nearest neighbors -> list[(distance, index)] sorted ascending."""
        query = np.asarray(query, np.float64)
        heap = []   # max-heap via negative distances

        def search(node):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            tau = -heap[0][0] if len(heap) == k else np.inf
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                search(node.inside)
                if d + tau > node.threshold:
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau <= node.threshold:
                    search(node.inside)

        search(self.root)
        return sorted((-nd, i) for nd, i in heap)


class KDTree:
    """Axis-aligned k-d tree. reference: clustering/kdtree/KDTree.java."""

    class _Node:
        __slots__ = ("index", "axis", "left", "right")

        def __init__(self, index, axis):
            self.index = index
            self.axis = axis
            self.left = None
            self.right = None

    def __init__(self, points):
        self.points = np.asarray(points, np.float64)
        self.dims = self.points.shape[1]
        self.root = self._build(list(range(len(self.points))), 0)

    def _build(self, idx, depth):
        if not idx:
            return None
        axis = depth % self.dims
        idx.sort(key=lambda i: self.points[i][axis])
        mid = len(idx) // 2
        node = KDTree._Node(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def nn(self, query):
        """Nearest neighbor -> (distance, index)."""
        query = np.asarray(query, np.float64)
        best = [np.inf, -1]

        def search(node):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - query))
            if d < best[0]:
                best[0], best[1] = d, node.index
            diff = query[node.axis] - self.points[node.index][node.axis]
            near, far = (node.left, node.right) if diff <= 0 else \
                        (node.right, node.left)
            search(near)
            if abs(diff) < best[0]:
                search(far)

        search(self.root)
        return best[0], best[1]
