"""ModelSerializer — checkpoint/restore in the reference's zip layout.

TPU-native equivalent of reference util/ModelSerializer.java:39-55:
a zip container with entries
  - configuration.json   (network configuration incl. iteration/epoch counters)
  - coefficients.bin     (the flattened params vector — same contract as
                          Nd4j.write of the reference's single params view)
  - updaterState.bin     (optimizer state arrays, flatten-order)
  - modelState.bin       (non-trainable layer state, e.g. BN running stats —
                          the reference stores these inside params; here they
                          are a separate pytree)
  - normalizer.json      (optional data normalizer)

Exact resume = params + updater state + counters (reference
NeuralNetConfiguration.iterationCount:119 lives in the config JSON).
"""
from __future__ import annotations

import io
import json
import zipfile

import jax
import numpy as np

CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.bin"
UPDATER_ENTRY = "updaterState.bin"
MODEL_STATE_ENTRY = "modelState.bin"
NORMALIZER_ENTRY = "normalizer.json"


def _save_tree(tree):
    """Serialize a pytree of arrays to npz bytes in flatten order. The
    structure itself is NOT stored — it is reconstructed from the network
    configuration on restore (deterministic), so the wire format stays a
    plain ordered list of arrays like the reference's .bin entries."""
    leaves = jax.tree_util.tree_leaves(tree)
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(l) for l in leaves])
    return buf.getvalue()


def _load_tree(data, like):
    """Load npz bytes into the structure of `like`."""
    leaves, treedef = jax.tree_util.tree_flatten(like)
    with np.load(io.BytesIO(data)) as z:
        loaded = [z[f"arr_{i}"] for i in range(len(z.files))]
    if len(loaded) != len(leaves):
        raise ValueError(f"Checkpoint has {len(loaded)} arrays, "
                         f"model expects {len(leaves)}")
    import jax.numpy as jnp
    new_leaves = [jnp.asarray(a, l.dtype) for a, l in zip(loaded, leaves)]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def write_model(model, path, save_updater=True, normalizer=None):
    """reference: ModelSerializer.writeModel:55-82."""
    model._ensure_init()
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(CONFIG_ENTRY, model.conf.to_json())
        buf = io.BytesIO()
        np.save(buf, model.params())
        zf.writestr(COEFFICIENTS_ENTRY, buf.getvalue())
        if save_updater and model._updater_state is not None:
            zf.writestr(UPDATER_ENTRY, _save_tree(model._updater_state))
        if model._model_state is not None:
            zf.writestr(MODEL_STATE_ENTRY, _save_tree(model._model_state))
        if normalizer is not None:
            zf.writestr(NORMALIZER_ENTRY, json.dumps(normalizer.to_dict()))


writeModel = write_model


def _restore(path, conf_cls, net_cls, load_updater=True):
    with zipfile.ZipFile(path, "r") as zf:
        conf = conf_cls.from_json(zf.read(CONFIG_ENTRY).decode("utf-8"))
        net = net_cls(conf).init()
        flat = np.load(io.BytesIO(zf.read(COEFFICIENTS_ENTRY)))
        net.set_params(flat)
        names = zf.namelist()
        if load_updater and UPDATER_ENTRY in names:
            net._updater_state = _load_tree(zf.read(UPDATER_ENTRY),
                                            net._updater_state)
        if MODEL_STATE_ENTRY in names:
            net._model_state = _load_tree(zf.read(MODEL_STATE_ENTRY),
                                          net._model_state)
        return net


def restore_multi_layer_network(path, load_updater=True):
    """reference: ModelSerializer.restoreMultiLayerNetwork:166."""
    from ..nn.conf.neural_net_configuration import MultiLayerConfiguration
    from ..nn.multilayer import MultiLayerNetwork
    return _restore(path, MultiLayerConfiguration, MultiLayerNetwork,
                    load_updater)


restoreMultiLayerNetwork = restore_multi_layer_network


def restore_computation_graph(path, load_updater=True):
    """reference: ModelSerializer.restoreComputationGraph:329."""
    from ..nn.conf.computation_graph_configuration import \
        ComputationGraphConfiguration
    from ..nn.graph import ComputationGraph
    return _restore(path, ComputationGraphConfiguration, ComputationGraph,
                    load_updater)


restoreComputationGraph = restore_computation_graph


def restore_normalizer(path):
    from ..datasets.normalizers import Normalizer
    with zipfile.ZipFile(path, "r") as zf:
        if NORMALIZER_ENTRY not in zf.namelist():
            return None
        return Normalizer.from_dict(
            json.loads(zf.read(NORMALIZER_ENTRY).decode("utf-8")))


def restore_model(path, load_updater=True):
    """Heuristic restore of either network type from the config JSON's format
    tag. reference: deeplearning4j-core util/ModelGuesser.java."""
    with zipfile.ZipFile(path, "r") as zf:
        cfg = json.loads(zf.read(CONFIG_ENTRY).decode("utf-8"))
    fmt = cfg.get("format", "")
    if "ComputationGraph" in fmt:
        return restore_computation_graph(path, load_updater)
    return restore_multi_layer_network(path, load_updater)
