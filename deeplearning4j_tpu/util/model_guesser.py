"""ModelGuesser — heuristically load any model or configuration artifact.

TPU-native equivalent of reference deeplearning4j-core
util/ModelGuesser.java: `loadModelGuess` tries the serialized-model formats
in turn (MultiLayerNetwork zip, ComputationGraph zip) and `loadConfigGuess`
tries every configuration representation (MultiLayerConfiguration /
ComputationGraphConfiguration as JSON or YAML).
"""
from __future__ import annotations

import json
import os
import zipfile

from . import model_serializer


def load_config_guess(path_or_str):
    """Parse a configuration from a file path or a raw JSON/YAML string,
    trying MultiLayerConfiguration then ComputationGraphConfiguration in
    each format. reference: ModelGuesser.loadConfigGuess."""
    from ..nn.conf.computation_graph_configuration import \
        ComputationGraphConfiguration
    from ..nn.conf.neural_net_configuration import MultiLayerConfiguration

    text = path_or_str
    if isinstance(path_or_str, (str, os.PathLike)) and \
            os.path.exists(str(path_or_str)):
        with open(path_or_str, "r", encoding="utf-8") as fh:
            text = fh.read()

    errors = []
    for parse in (json.loads, _yaml_load):
        try:
            d = parse(text)
        except Exception as e:
            errors.append(e)
            continue
        if not isinstance(d, dict):
            errors.append(ValueError("not a mapping"))
            continue
        fmt = d.get("format", "")
        order = ([ComputationGraphConfiguration, MultiLayerConfiguration]
                 if "ComputationGraph" in fmt
                 else [MultiLayerConfiguration, ComputationGraphConfiguration])
        for cls in order:
            try:
                return cls.from_dict(d)
            except Exception as e:
                errors.append(e)
    raise ValueError(
        f"Unable to guess configuration format ({len(errors)} attempts): "
        f"{errors[-1] if errors else 'empty input'}")


loadConfigGuess = load_config_guess


def load_model_guess(path, load_updater=True):
    """Load a model OR a bare configuration from `path`, whichever it is.
    Zip archives restore a full network (params + updater state); JSON/YAML
    files produce an uninitialized network from the parsed configuration.
    reference: ModelGuesser.loadModelGuess."""
    p = str(path)
    if zipfile.is_zipfile(p):
        return model_serializer.restore_model(p, load_updater)
    conf = load_config_guess(p)
    from ..nn.conf.computation_graph_configuration import \
        ComputationGraphConfiguration
    if isinstance(conf, ComputationGraphConfiguration):
        from ..nn.graph import ComputationGraph
        return ComputationGraph(conf)
    from ..nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(conf)


loadModelGuess = load_model_guess


def _yaml_load(text):
    import yaml
    return yaml.safe_load(text)
