from . import model_serializer as ModelSerializer  # noqa: N812
from .model_serializer import (restore_computation_graph, restore_model,
                               restore_multi_layer_network, write_model)

__all__ = ["ModelSerializer", "restore_computation_graph", "restore_model",
           "restore_multi_layer_network", "write_model"]
