from . import model_guesser as ModelGuesser  # noqa: N812
from . import model_serializer as ModelSerializer  # noqa: N812
from .model_guesser import load_config_guess, load_model_guess
from .model_serializer import (restore_computation_graph, restore_model,
                               restore_multi_layer_network, write_model)
from .sharded_checkpoint import (ShardedCheckpointManager,
                                 ShardedModelSaver,
                                 load_checkpoint, save_checkpoint)

__all__ = ["ModelGuesser", "ModelSerializer",
           "ShardedCheckpointManager", "ShardedModelSaver",
           "load_checkpoint",
           "save_checkpoint", "load_config_guess",
           "load_model_guess", "restore_computation_graph", "restore_model",
           "restore_multi_layer_network", "write_model"]
