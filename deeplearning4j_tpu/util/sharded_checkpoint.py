"""Mesh-sharded checkpointing (orbax-backed).

The reference's ModelSerializer writes ONE zip from one JVM
(util/ModelSerializer.java — configuration.json + coefficients.bin +
updaterState.bin), which `util/model_serializer.py` mirrors byte-format-
exactly for single-host parity. THIS module is the TPU-first scale path the
reference cannot express: parameters, updater state and model state are
saved AS SHARDED jax.Arrays — on a multi-host mesh every process writes
only its own shards (orbax coordinates the global commit), and restore
places each shard directly onto the devices of whatever sharding the
target network currently holds (replicated single-chip, ZeRO-partitioned
optimizer state, tensor-parallel splits — anything). No host ever
materializes the full parameter set, which is what makes
beyond-single-host-memory models checkpointable at all.

Usage:
    save_checkpoint(net, "/ckpts/step1000")      # all processes call
    net2 = MultiLayerNetwork(conf).init()        # same architecture
    pw = ParallelWrapper.Builder(net2)...build() # optional: shard first
    load_checkpoint(net2, "/ckpts/step1000")     # restores INTO the
                                                 # current sharding layout

The zip serializer remains the interchange format; this is the
training-scale format (resume-exact: counters, rng, updater state and the
device-resident loop state all round-trip).
"""
from __future__ import annotations

import os

import jax
import numpy as np


def _tree(net):
    """The checkpointable pytree: everything exact resume needs. The
    structure is FIXED (no optional keys) so a template built from any
    same-architecture net always matches the saved tree: when the device
    loop state doesn't exist yet, a structurally-identical placeholder is
    stored and `has_loop` records which it was."""
    import jax.numpy as jnp
    loop = getattr(net, "_loop", None)
    return {
        "params": net._params,
        "updater_state": net._updater_state,
        "model_state": net._model_state,
        "rng": net._rng,
        "iteration_count": int(net.conf.iteration_count),
        "epoch_count": int(getattr(net.conf, "epoch_count", 0)),
        "has_loop": loop is not None,
        "loop": (loop if loop is not None
                 else {"iteration": jnp.asarray(0.0, jnp.float32),
                       "rng": net._rng}),
    }


def _serializable(tree):
    """Multi-host: host-local (fully-addressable) jax.Arrays — loop
    scalars, rng keys, anything not yet mesh-sharded — cannot be
    serialized as global arrays; they are identical on every process, so
    ship them as numpy (orbax writes replicated values from the primary).
    Global sharded arrays pass through untouched (per-process shard
    writes). Single-host: no-op."""
    if jax.process_count() == 1:
        return tree
    return jax.tree.map(
        lambda a: (np.asarray(a)
                   if isinstance(a, jax.Array) and a.is_fully_addressable
                   else a), tree)


def save_checkpoint(net, path, overwrite=True):
    """Save a network's full training state with per-process shard writes.
    On a multi-host mesh EVERY process must call this (orbax coordinates
    the commit); single-host it is an ordinary atomic checkpoint dir.
    `overwrite=True` (default) replaces an existing checkpoint at `path`
    (the fixed-path periodic-save pattern, matching ModelSerializer's
    overwrite semantics); False raises if the destination exists."""
    import orbax.checkpoint as ocp
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), _serializable(_tree(net)),
                   force=bool(overwrite))
        ckptr.wait_until_finished()


class ShardedCheckpointManager:
    """Step-numbered sharded checkpoints with retention — the
    CheckpointListener/CheckpointManager role over the mesh-sharded
    format: keep the last `keep_last` steps plus the best-scoring one,
    prune the rest.

    Layout: `<directory>/ckpt_<step>/` per checkpoint +
    `<directory>/manager.json` metadata (steps, scores, best). On a
    multi-host mesh every process calls `save` (per-process shard
    writes); metadata writes and pruning happen on process 0 only."""

    def __init__(self, directory, keep_last=3, mode="min"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.directory = os.path.abspath(directory)
        self.keep_last = max(1, int(keep_last))
        self.mode = mode
        os.makedirs(self.directory, exist_ok=True)
        self._meta_path = os.path.join(self.directory, "manager.json")
        self._meta = {"steps": [], "scores": {}}
        if os.path.exists(self._meta_path):
            import json
            with open(self._meta_path) as f:
                self._meta = json.load(f)
            # retention policy is PERSISTED and validated: resuming with a
            # different mode would invert best_step and prune the true
            # best checkpoint — fail loudly instead
            for key, mine in (("mode", self.mode),
                              ("keep_last", self.keep_last)):
                stored = self._meta.get(key)
                if stored is not None and stored != mine:
                    raise ValueError(
                        f"checkpoint dir was managed with {key}={stored!r}"
                        f"; refusing to resume with {key}={mine!r} (pass "
                        f"the original value)")

    def _path(self, step):
        return os.path.join(self.directory, f"ckpt_{int(step)}")

    def steps(self):
        return list(self._meta["steps"])

    def latest_step(self):
        """Newest checkpointed step, or None for an empty directory — the
        crash-resume probe (TrainingMaster/ParallelWrapper fast-forward
        past this many averaging rounds on a re-run)."""
        return self._meta["steps"][-1] if self._meta["steps"] else None

    def best_step(self):
        scores = {int(s): v for s, v in self._meta["scores"].items()
                  if v is not None}
        if not scores:
            return None
        if self.mode == "min":
            # latest wins ties: smaller score first, then larger step
            return min(scores, key=lambda s: (scores[s], -s))
        return max(scores, key=lambda s: (scores[s], s))

    def save(self, net, step, score=None):
        """Checkpoint `net` at `step` (optionally scored), then prune to
        the retention policy. Returns the checkpoint path.

        Crash-safety ordering: the checkpoint is committed first (orbax is
        atomic), then the metadata is REPLACED atomically, and only then
        are pruned directories deleted — a crash at any point leaves
        metadata that references only fully-committed checkpoints (at
        worst some orphan directories, swept on the next save)."""
        step = int(step)
        path = self._path(step)
        save_checkpoint(net, path)
        if step not in self._meta["steps"]:
            self._meta["steps"].append(step)
            self._meta["steps"].sort()
        if score is not None or str(step) not in self._meta["scores"]:
            # never erase a recorded score with a score-less re-save: the
            # former best must not silently become prunable
            self._meta["scores"][str(step)] = (None if score is None
                                               else float(score))
        stale = self._compute_prune()
        self._write_meta()
        if jax.process_index() == 0:
            import shutil
            for s in stale:
                shutil.rmtree(self._path(s), ignore_errors=True)
            self._sweep_orphans()
        return path

    def _compute_prune(self):
        """Drop out-of-policy steps from the metadata; return them (the
        directories are deleted AFTER the metadata write)."""
        keep = set(self._meta["steps"][-self.keep_last:])
        best = self.best_step()
        if best is not None:
            keep.add(best)
        stale = [s for s in self._meta["steps"] if s not in keep]
        for step in stale:
            self._meta["steps"].remove(step)
            self._meta["scores"].pop(str(step), None)
        return stale

    def _write_meta(self):
        if jax.process_index() != 0:
            return
        import json
        self._meta["mode"] = self.mode
        self._meta["keep_last"] = self.keep_last
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._meta, f)
        os.replace(tmp, self._meta_path)       # atomic on POSIX

    def _sweep_orphans(self):
        """Delete ckpt_<step> dirs the metadata no longer references
        (left by a crash between metadata write and deletion)."""
        import shutil
        live = {f"ckpt_{s}" for s in self._meta["steps"]}
        for name in os.listdir(self.directory):
            if (name.startswith("ckpt_") and name not in live
                    and name[len("ckpt_"):].isdigit()):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def restore(self, net, step):
        return load_checkpoint(net, self._path(int(step)))

    def restore_latest(self, net):
        if not self._meta["steps"]:
            raise FileNotFoundError(f"no checkpoints under "
                                    f"{self.directory!r}")
        return self.restore(net, self._meta["steps"][-1])

    def restore_best(self, net):
        best = self.best_step()
        if best is None:
            raise FileNotFoundError("no SCORED checkpoints under "
                                    f"{self.directory!r}")
        return self.restore(net, best)


class RoundCheckpointer:
    """Per-averaging-round checkpoint + crash-resume gate — the ONE
    implementation of the resume protocol shared by
    `ParameterAveragingTrainingMaster` and `ParallelWrapper` (the round is
    the resume unit: master = one split; wrapper = one batch in allreduce
    mode / one k-group in k-step mode).

    With `directory=None` it is a pure round counter (checkpointing off).
    Otherwise: `maybe_resume(net)` — once per lifetime, and only into a
    never-trained net (iteration_count 0; a warm net is an in-process
    continuation, not a crash restart) — restores the newest checkpoint
    and records how many rounds it covers; `round_starts()` then gates
    those rounds off (the caller still consumes their batches so the data
    stream stays aligned); `round_done(net)` saves every `every` rounds.
    Re-running the same training command after a crash therefore resumes
    from the last completed averaging round with the exact rng/counters,
    making the result bit-comparable to an uninterrupted run."""

    def __init__(self, directory=None, every=1, keep_last=3, resume=True,
                 owner="trainer"):
        self.directory = None if directory is None else str(directory)
        self.every = max(1, int(every))
        self.keep_last = max(1, int(keep_last))
        self.resume = bool(resume)
        self.owner = owner
        self.round = 0           # rounds dispatched, monotonic for life
        self.resume_round = 0    # rounds covered by a restored checkpoint
        self._mgr = None
        self._checked = False

    def manager(self):
        if self.directory is None:
            return None
        if self._mgr is None:
            self._mgr = ShardedCheckpointManager(self.directory,
                                                 keep_last=self.keep_last)
        return self._mgr

    def maybe_resume(self, net):
        if self._checked:
            return
        self._checked = True
        mgr = self.manager()
        if mgr is None:
            return
        last = mgr.latest_step()
        if (not self.resume or last is None
                or net.conf.iteration_count != 0):
            return
        mgr.restore(net, last)
        self.resume_round = last
        import logging
        logging.getLogger(__name__).warning(
            "%s: resuming from checkpoint round %d under %s — "
            "fast-forwarding past the already-trained rounds of the "
            "re-run", self.owner, last, self.directory)

    def round_starts(self):
        """True when this round must actually run; False when a restored
        checkpoint already contains it."""
        r = self.round
        self.round += 1
        return r >= self.resume_round

    def round_done(self, net):
        mgr = self.manager()
        if mgr is None or self.round % self.every != 0:
            return
        score = getattr(net, "_score", None)
        mgr.save(net, self.round,
                 score=None if score is None else float(score))


class ShardedModelSaver:
    """Early-stopping saver SPI over the sharded format (reference
    earlystopping/saver/LocalFileModelSaver.java, which writes the zip).
    The sharded format is not self-describing (no embedded conf), so the
    saver takes `net_factory` — a zero-arg callable building the same
    architecture — for the restore side."""

    def __init__(self, directory, net_factory):
        self.directory = os.path.abspath(directory)
        self.net_factory = net_factory
        os.makedirs(self.directory, exist_ok=True)

    @property
    def best_path(self):
        return os.path.join(self.directory, "bestModel")

    @property
    def latest_path(self):
        return os.path.join(self.directory, "latestModel")

    def save_best_model(self, net, score):
        save_checkpoint(net, self.best_path)

    def save_latest_model(self, net, score):
        save_checkpoint(net, self.latest_path)

    def get_best_model(self):
        return load_checkpoint(self.net_factory(), self.best_path)

    def get_latest_model(self):
        return load_checkpoint(self.net_factory(), self.latest_path)

    saveBestModel = save_best_model
    getBestModel = get_best_model


def _check_restore_shapes(tpl, metadata):
    """Loud architecture check: orbax (0.7) silently restores the SAVED
    shape when the template disagrees, so a checkpoint restored into the
    wrong architecture would hand the net mis-shaped parameters that only
    blow up (or worse, silently mistrain) later. Compare every array leaf
    the template and the stored metadata share and fail with the full
    mismatch list instead."""
    def flat(tree):
        out = {}
        for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            shape = getattr(leaf, "shape", None)
            if shape is not None:
                out[jax.tree_util.keystr(kp)] = tuple(shape)
        return out
    want, saved = flat(tpl), flat(metadata)
    bad = sorted(k for k in want.keys() & saved.keys()
                 if want[k] != saved[k])
    if bad:
        detail = "; ".join(f"{k}: saved {saved[k]} vs net {want[k]}"
                           for k in bad[:8])
        raise ValueError(
            f"checkpoint does not match the target architecture "
            f"({len(bad)} mismatched arrays): {detail}")


def load_checkpoint(net, path):
    """Restore a checkpoint INTO `net`, placing every shard onto the
    sharding each array currently has (shard a fresh net first — e.g. via
    ParallelWrapper's ZeRO/TP layouts — and the restore lands distributed;
    leave it unsharded and the restore lands replicated/local). The
    architecture must match the saved one (same pytree structure/shapes) —
    a mismatch raises instead of silently restoring the saved shapes.
    Returns `net`."""
    import orbax.checkpoint as ocp
    net._ensure_init()

    multi = jax.process_count() > 1

    def abstract(a):
        if isinstance(a, jax.Array):
            if multi and a.is_fully_addressable:
                # saved as replicated numpy (see _serializable) — restore
                # the same way; the first jit call device-puts it
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)
        if isinstance(a, np.ndarray):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a
    tpl = jax.tree.map(abstract, _tree(net))
    with ocp.StandardCheckpointer() as ckptr:
        try:
            metadata = ckptr.metadata(os.path.abspath(path))
        except Exception:  # noqa: BLE001 — older layouts: let orbax decide
            metadata = None
        if metadata is not None:
            _check_restore_shapes(tpl, metadata)
        doc = ckptr.restore(os.path.abspath(path), tpl)
    net._params = doc["params"]
    net._updater_state = doc["updater_state"]
    net._model_state = doc["model_state"]
    net._rng = doc["rng"]
    net.conf.iteration_count = int(doc["iteration_count"])
    if hasattr(net.conf, "epoch_count"):
        net.conf.epoch_count = int(doc["epoch_count"])
    net._loop = doc["loop"] if doc["has_loop"] else None
    return net
