"""Mesh-sharded checkpointing (orbax-backed).

The reference's ModelSerializer writes ONE zip from one JVM
(util/ModelSerializer.java — configuration.json + coefficients.bin +
updaterState.bin), which `util/model_serializer.py` mirrors byte-format-
exactly for single-host parity. THIS module is the TPU-first scale path the
reference cannot express: parameters, updater state and model state are
saved AS SHARDED jax.Arrays — on a multi-host mesh every process writes
only its own shards (orbax coordinates the global commit), and restore
places each shard directly onto the devices of whatever sharding the
target network currently holds (replicated single-chip, ZeRO-partitioned
optimizer state, tensor-parallel splits — anything). No host ever
materializes the full parameter set, which is what makes
beyond-single-host-memory models checkpointable at all.

Usage:
    save_checkpoint(net, "/ckpts/step1000")      # all processes call
    net2 = MultiLayerNetwork(conf).init()        # same architecture
    pw = ParallelWrapper.Builder(net2)...build() # optional: shard first
    load_checkpoint(net2, "/ckpts/step1000")     # restores INTO the
                                                 # current sharding layout

The zip serializer remains the interchange format; this is the
training-scale format (resume-exact: counters, rng, updater state and the
device-resident loop state all round-trip).
"""
from __future__ import annotations

import os

import jax
import numpy as np


def _tree(net):
    """The checkpointable pytree: everything exact resume needs. The
    structure is FIXED (no optional keys) so a template built from any
    same-architecture net always matches the saved tree: when the device
    loop state doesn't exist yet, a structurally-identical placeholder is
    stored and `has_loop` records which it was."""
    import jax.numpy as jnp
    loop = getattr(net, "_loop", None)
    return {
        "params": net._params,
        "updater_state": net._updater_state,
        "model_state": net._model_state,
        "rng": net._rng,
        "iteration_count": int(net.conf.iteration_count),
        "epoch_count": int(getattr(net.conf, "epoch_count", 0)),
        "has_loop": loop is not None,
        "loop": (loop if loop is not None
                 else {"iteration": jnp.asarray(0.0, jnp.float32),
                       "rng": net._rng}),
    }


def _serializable(tree):
    """Multi-host: host-local (fully-addressable) jax.Arrays — loop
    scalars, rng keys, anything not yet mesh-sharded — cannot be
    serialized as global arrays; they are identical on every process, so
    ship them as numpy (orbax writes replicated values from the primary).
    Global sharded arrays pass through untouched (per-process shard
    writes). Single-host: no-op."""
    if jax.process_count() == 1:
        return tree
    return jax.tree.map(
        lambda a: (np.asarray(a)
                   if isinstance(a, jax.Array) and a.is_fully_addressable
                   else a), tree)


def save_checkpoint(net, path, overwrite=True):
    """Save a network's full training state with per-process shard writes.
    On a multi-host mesh EVERY process must call this (orbax coordinates
    the commit); single-host it is an ordinary atomic checkpoint dir.
    `overwrite=True` (default) replaces an existing checkpoint at `path`
    (the fixed-path periodic-save pattern, matching ModelSerializer's
    overwrite semantics); False raises if the destination exists."""
    import orbax.checkpoint as ocp
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), _serializable(_tree(net)),
                   force=bool(overwrite))
        ckptr.wait_until_finished()


def load_checkpoint(net, path):
    """Restore a checkpoint INTO `net`, placing every shard onto the
    sharding each array currently has (shard a fresh net first — e.g. via
    ParallelWrapper's ZeRO/TP layouts — and the restore lands distributed;
    leave it unsharded and the restore lands replicated/local). The
    architecture must match the saved one (same pytree structure/shapes).
    Returns `net`."""
    import orbax.checkpoint as ocp
    net._ensure_init()

    multi = jax.process_count() > 1

    def abstract(a):
        if isinstance(a, jax.Array):
            if multi and a.is_fully_addressable:
                # saved as replicated numpy (see _serializable) — restore
                # the same way; the first jit call device-puts it
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)
        if isinstance(a, np.ndarray):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a
    tpl = jax.tree.map(abstract, _tree(net))
    with ocp.StandardCheckpointer() as ckptr:
        doc = ckptr.restore(os.path.abspath(path), tpl)
    net._params = doc["params"]
    net._updater_state = doc["updater_state"]
    net._model_state = doc["model_state"]
    net._rng = doc["rng"]
    net.conf.iteration_count = int(doc["iteration_count"])
    if hasattr(net.conf, "epoch_count"):
        net.conf.epoch_count = int(doc["epoch_count"])
    net._loop = doc["loop"] if doc["has_loop"] else None
    return net
