from .server import DeepLearning4jEntryPoint, KerasBridgeServer
from .keras_import import (import_keras_model_and_weights,
                           import_keras_model_configuration,
                           import_keras_sequential_model_and_weights)

KerasModelImport = __import__(
    "deeplearning4j_tpu.keras.keras_import", fromlist=["keras_import"])

__all__ = ["DeepLearning4jEntryPoint", "KerasBridgeServer",
           "KerasModelImport", "import_keras_model_and_weights",
           "import_keras_model_configuration",
           "import_keras_sequential_model_and_weights"]
