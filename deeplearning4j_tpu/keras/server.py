"""Keras backend bridge — external processes drive training over RPC.

TPU-native equivalent of reference deeplearning4j-keras: Server.java:18
runs a Py4J GatewayServer exposing DeepLearning4jEntryPoint.fit() so the
Python Keras wrapper can hand a Keras model + HDF5-exported batches to the
JVM runtime. This runtime already IS Python, so the bridge becomes a
language-agnostic HTTP gateway with the same entry points:

  POST /fit      {"model_path", "features_path", "labels_path",
                  "nb_epoch"?, "batch_size"?}     -> {"score": ...}
  POST /predict  {"model_path", "features_path"}  -> {"predictions": [...]}
  GET  /health                                    -> {"ok": true}

Models are imported through keras_import (KerasModelImport role,
NeuralNetworkReader.java) and cached per path; data files are .h5 (datasets
"features"/"labels", the HDF5MiniBatchDataSetIterator layout) or .npz.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..datasets.iterators import next_processed


def _load_array(path, key):
    if str(path).endswith((".h5", ".hdf5")):
        import h5py
        with h5py.File(path, "r") as f:
            if key in f:
                return np.asarray(f[key])
            # single-dataset files (the per-batch export layout)
            names = list(f.keys())
            if len(names) == 1:
                return np.asarray(f[names[0]])
            raise KeyError(f"no dataset '{key}' in {path} (has {names})")
    with np.load(path) as z:
        return np.asarray(z[key] if key in z else z[list(z.files)[0]])


class HDF5MiniBatchDataSetIterator:
    """Batches from features/labels array files — reference
    keras/HDF5MiniBatchDataSetIterator.java (directory-of-batches there,
    one array file sliced here; both feed fit() identically)."""

    def __init__(self, features_path, labels_path, batch_size=32):
        from ..datasets.dataset import DataSet
        x = _load_array(features_path, "features")
        y = _load_array(labels_path, "labels")
        self._batches = list(DataSet(x, y).batch_by(int(batch_size)))
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._batches)

    def next_batch(self):
        b = self._batches[self._pos]
        self._pos += 1
        return b

    def reset(self):
        self._pos = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_batch()


class DeepLearning4jEntryPoint:
    """reference: keras/DeepLearning4jEntryPoint.java — fit/predict on a
    Keras-defined model, models cached per path."""

    def __init__(self):
        self._models = {}      # path -> (net, per-model lock)
        self._lock = threading.Lock()

    def _model(self, model_path):
        with self._lock:
            if model_path not in self._models:
                from .keras_import import \
                    import_keras_sequential_model_and_weights
                try:
                    net = import_keras_sequential_model_and_weights(
                        model_path)
                except Exception:
                    from .keras_import import import_keras_model_and_weights
                    net = import_keras_model_and_weights(model_path)
                self._models[model_path] = (net, threading.Lock())
            return self._models[model_path]

    def fit(self, model_path, features_path, labels_path, nb_epoch=1,
            batch_size=32):
        net, mlock = self._model(model_path)
        it = HDF5MiniBatchDataSetIterator(features_path, labels_path,
                                          batch_size)
        # serialize per model: the threaded HTTP server would otherwise
        # race concurrent fit() calls on the same cached network
        with mlock:
            for _ in range(int(nb_epoch)):
                it.reset()
                while it.has_next():
                    net.fit(next_processed(it))
            return float(net.score())

    def predict(self, model_path, features_path):
        net, mlock = self._model(model_path)
        x = _load_array(features_path, "features")
        with mlock:
            out = net.output(x)
        if isinstance(out, (list, tuple)):
            out = out[0]
        return np.asarray(out)


class KerasBridgeServer:
    """reference: keras/Server.java (GatewayServer -> HTTP here)."""

    def __init__(self, port=0):
        self.port = int(port)
        self.entry_point = DeepLearning4jEntryPoint()
        self._httpd = None
        self._thread = None

    def start(self):
        ep = self.entry_point

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._json({"ok": True})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._json({"error": "bad json"}, 400)
                    return
                try:
                    if self.path == "/fit":
                        score = ep.fit(req["model_path"],
                                       req["features_path"],
                                       req["labels_path"],
                                       req.get("nb_epoch", 1),
                                       req.get("batch_size", 32))
                        self._json({"score": score})
                    elif self.path == "/predict":
                        preds = ep.predict(req["model_path"],
                                           req["features_path"])
                        self._json({"predictions": preds.tolist()})
                    else:
                        self._json({"error": "not found"}, 404)
                except KeyError as e:
                    self._json({"error": f"missing field {e}"}, 400)
                except Exception as e:   # surface the failure to the caller
                    self._json({"error": str(e)[:500]}, 500)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
