"""Keras 1.x model import (JSON topology + HDF5 weights).

TPU-native equivalent of reference deeplearning4j-modelimport:
KerasModelImport (KerasModelImport.java:85-250), KerasModel/
KerasSequentialModel (KerasModel.java:57), per-layer mapping (KerasLayer.java,
1,111 LoC). The reference reads HDF5 through JavaCPP; here h5py plays that
role.

Supported layers (the reference's set, KerasLayer.java): Dense,
Convolution2D, MaxPooling2D, AveragePooling2D, LSTM, Embedding,
BatchNormalization, Activation, Dropout, Flatten, Reshape, ZeroPadding2D,
Merge (sequential path treats structural layers as preprocessor hints).

Dim-ordering: Keras 1 'th' (NCHW) and 'tf' (NHWC) are both handled; since
this framework is NHWC-native, 'th' conv kernels are transposed
OIHW -> HWIO and the first post-Flatten Dense has its rows permuted from
CHW to HWC order (the reference does the same NCHW bookkeeping in
KerasModel.copyWeights).
"""
from __future__ import annotations

import json

import numpy as np

from ..nn.conf.input_type import InputType
from ..nn.conf.layers import (ActivationLayer, BatchNormalization,
                              ConvolutionLayer, DenseLayer, DropoutLayer,
                              EmbeddingLayer, GravesLSTM, LossLayer,
                              OutputLayer, SubsamplingLayer, ZeroPaddingLayer)
from ..nn.conf.neural_net_configuration import NeuralNetConfiguration

_ACTIVATION_MAP = {
    "linear": "identity", "relu": "relu", "tanh": "tanh",
    "sigmoid": "sigmoid", "softmax": "softmax", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
}


def _map_activation(name):
    if name not in _ACTIVATION_MAP:
        raise ValueError(f"Unsupported Keras activation '{name}'")
    return _ACTIVATION_MAP[name]


# ---------------------------------------------------------------------------
# Public API — reference KerasModelImport.java
# ---------------------------------------------------------------------------

def _read_model_file(h5_path):
    import h5py
    with h5py.File(h5_path, "r") as f:
        cfg = f.attrs["model_config"]
        if isinstance(cfg, bytes):
            cfg = cfg.decode("utf-8")
        model_cfg = json.loads(cfg)
        weights = _read_weight_groups(f["model_weights"]
                                      if "model_weights" in f else f)
    return model_cfg, weights


def import_keras_sequential_model_and_weights(h5_path):
    """Read a Keras 1.x sequential model saved via model.save(): topology from
    the `model_config` attribute, weights from `model_weights`.
    reference: KerasModelImport.importKerasSequentialModelAndWeights."""
    model_cfg, weights = _read_model_file(h5_path)
    return _build_sequential(model_cfg, weights)


importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights


def import_keras_model_and_weights(h5_path):
    """Read a Keras model saved via model.save(). Sequential models build a
    MultiLayerNetwork; functional `Model`s (Keras 1 "Model" / modern
    "Functional") build a ComputationGraph — the reference's primary import
    path (KerasModel.java:57 -> ComputationGraphConfiguration,
    KerasModelImport.java:135).

    Both the Keras 1.x config dialect (output_dim / nb_filter / Merge with
    mode=...) and the modern dialect (units / filters / Add / Concatenate)
    are understood, so fixtures written by today's Keras import identically
    to period files."""
    model_cfg, weights = _read_model_file(h5_path)
    if model_cfg.get("class_name") == "Sequential":
        return _build_sequential(model_cfg, weights)
    return _build_functional(model_cfg, weights)


importKerasModelAndWeights = import_keras_model_and_weights


def import_keras_model_configuration(json_path_or_str):
    """Topology-only import (no weights).
    reference: KerasModelImport.importKerasModelConfiguration."""
    s = json_path_or_str
    if not s.lstrip().startswith("{"):
        with open(s, "r", encoding="utf-8") as fh:
            s = fh.read()
    model_cfg = json.loads(s)
    return _build_sequential(model_cfg, weights=None, conf_only=True)


importKerasModelConfiguration = import_keras_model_configuration


def _read_weight_groups(g):
    """layer-name -> list of arrays, in `weight_names` attribute order."""
    out = {}
    for lname in g:
        grp = g[lname]
        if "weight_names" in grp.attrs:
            names = [n.decode() if isinstance(n, bytes) else n
                     for n in grp.attrs["weight_names"]]
            out[lname] = [np.asarray(grp[n]) for n in names]
        else:
            out[lname] = [np.asarray(grp[d]) for d in sorted(grp)]
    return out


# ---------------------------------------------------------------------------
# Sequential build
# ---------------------------------------------------------------------------

def _build_sequential(model_cfg, weights, conf_only=False):
    if model_cfg.get("class_name") != "Sequential":
        raise ValueError(
            f"Expected Sequential model, got {model_cfg.get('class_name')} "
            "(functional Model import: use the ComputationGraph path)")
    layer_cfgs = model_cfg["config"]
    if isinstance(layer_cfgs, dict):   # keras 2 style {"layers": [...]}
        layer_cfgs = layer_cfgs["layers"]

    builder = (NeuralNetConfiguration.Builder().seed(12345).list())
    input_type, dim_ordering = _input_type_of(layer_cfgs[0])

    mapped = []        # (our LayerConf or None, keras cfg)
    flatten_perm = []  # indices of our-layers needing th->HWC row permute
    pending_flatten_shape = None
    idx = 0
    for lc in layer_cfgs:
        cls = lc["class_name"]
        cfg = lc["config"]
        layer, is_structural = _map_layer(cls, cfg, dim_ordering)
        if cls == "Flatten":
            pending_flatten_shape = "flatten"
            mapped.append((None, lc))
            continue
        if layer is None:
            mapped.append((None, lc))
            continue
        if (pending_flatten_shape and isinstance(layer, DenseLayer)
                and dim_ordering == "th"):
            flatten_perm.append(idx)
        pending_flatten_shape = None
        builder.layer(idx, layer)
        mapped.append((layer, lc))
        idx += 1

    # a trailing classifier head becomes a trainable loss head, like the
    # functional path (Keras models carry the loss in compile(), which
    # model_config does not serialize — infer it from the activation).
    # Two Keras idioms: Dense(softmax) directly, and the Keras-1 classic
    # Dense(linear) followed by a separate Activation('softmax') layer.
    last_i = next((i for i in range(len(mapped) - 1, -1, -1)
                   if mapped[i][0] is not None), None)
    last = mapped[last_i][0] if last_i is not None else None
    if (isinstance(last, DenseLayer) and not isinstance(last, OutputLayer)
            and last.activation in ("softmax", "sigmoid")):
        loss = "mcxent" if last.activation == "softmax" else "xent"
        out = OutputLayer(n_out=last.n_out, n_in=last.n_in,
                          activation=last.activation, loss_function=loss)
        builder.layer(idx - 1, out)
        # keep the replaced layer's OWN keras config paired (weight copy
        # matches entries by that config's class/name)
        mapped[last_i] = (out, mapped[last_i][1])
    elif (isinstance(last, ActivationLayer)
            and last.activation in ("softmax", "sigmoid")):
        loss = "mcxent" if last.activation == "softmax" else "xent"
        head = LossLayer(activation=last.activation, loss_function=loss)
        builder.layer(idx - 1, head)
        mapped[last_i] = (head, mapped[last_i][1])

    builder.set_input_type(input_type)
    conf = builder.build()
    from ..nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(conf)
    if conf_only:
        return conf
    net.init()
    if weights is not None:
        _copy_weights(net, mapped, weights, flatten_perm, conf)
    return net


def _input_type_of(first_layer_cfg):
    cfg = first_layer_cfg["config"]
    shape = cfg.get("batch_input_shape")
    dim_ordering = cfg.get("dim_ordering", "tf")
    if shape is None:
        raise ValueError("First layer has no batch_input_shape")
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0]), dim_ordering
    if len(dims) == 2:
        return InputType.recurrent(dims[1]), dim_ordering
    if len(dims) == 3:
        if dim_ordering == "th":   # (C, H, W)
            c, h, w = dims
        else:                      # (H, W, C)
            h, w, c = dims
        return InputType.convolutional(h, w, c), dim_ordering
    raise ValueError(f"Unsupported input shape {shape}")


def _pair_of(v, default):
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return (int(v), int(v))
    return tuple(int(x) for x in v)


def _map_layer(cls, cfg, dim_ordering):
    """Keras layer config -> our LayerConf (or None for structural layers).
    Understands both the Keras 1 dialect (output_dim / nb_filter / nb_row /
    subsample / border_mode / p) and the modern one (units / filters /
    kernel_size / strides / padding / rate).
    reference: KerasLayer layer-by-layer mapping."""
    act = cfg.get("activation", "linear")
    same = (cfg.get("border_mode") or cfg.get("padding", "valid")) == "same"
    if cls == "Dense":
        n_out = cfg.get("output_dim", cfg.get("units"))
        return DenseLayer(n_out=int(n_out),
                          activation=_map_activation(act)), False
    if cls in ("Convolution2D", "Conv2D"):
        n_out = cfg.get("nb_filter", cfg.get("filters"))
        if "nb_row" in cfg:
            kernel = (int(cfg["nb_row"]), int(cfg["nb_col"]))
        else:
            kernel = _pair_of(cfg.get("kernel_size"), (3, 3))
        stride = _pair_of(cfg.get("subsample") or cfg.get("strides"), (1, 1))
        has_bias = bool(cfg.get("use_bias", cfg.get("bias", True)))
        return ConvolutionLayer(
            n_out=int(n_out), kernel_size=kernel, stride=stride,
            convolution_mode=("same" if same else "truncate"),
            activation=_map_activation(act), has_bias=has_bias), False
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        pool = _pair_of(cfg.get("pool_size"), (2, 2))
        return SubsamplingLayer(
            pooling_type="max" if cls.startswith("Max") else "avg",
            kernel_size=pool,
            stride=_pair_of(cfg.get("strides"), pool),
            convolution_mode=("same" if same else "truncate")), False
    if cls == "LSTM":
        n_out = cfg.get("output_dim", cfg.get("units"))
        return GravesLSTM(n_out=int(n_out),
                          activation=_map_activation(act),
                          gate_activation=_map_activation(
                              cfg.get("inner_activation",
                                      cfg.get("recurrent_activation",
                                              "hard_sigmoid"))),
                          forget_gate_bias_init=0.0), False
    if cls == "Embedding":
        return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                              n_out=int(cfg["output_dim"]),
                              activation="identity"), False
    if cls == "BatchNormalization":
        return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                  decay=float(cfg.get("momentum", 0.99))), False
    if cls == "Activation":
        return ActivationLayer(activation=_map_activation(act)), False
    if cls == "Dropout":
        # Keras p/rate = drop probability; ours = retain probability
        p = cfg.get("p", cfg.get("rate", 0.5))
        return DropoutLayer(dropout=1.0 - float(p)), False
    if cls == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and pad and \
                isinstance(pad[0], (list, tuple)):
            # ((top,bottom),(left,right)) form: only symmetric pads map onto
            # ZeroPaddingLayer's (ph, pw); silently dropping bottom/right
            # would import a model that computes different activations
            if pad[0][0] != pad[0][1] or pad[1][0] != pad[1][1]:
                raise ValueError(
                    f"Asymmetric ZeroPadding2D {tuple(map(tuple, pad))} is "
                    f"not supported (top!=bottom or left!=right)")
            pad = (pad[0][0], pad[1][0])
        return ZeroPaddingLayer(pad=_pair_of(pad, (1, 1))), False
    if cls in ("Flatten", "Reshape", "InputLayer"):
        return None, True
    raise ValueError(f"Unsupported Keras layer type '{cls}'")


# Merge-style layers -> graph vertices (functional path only).
# Keras 1: one "Merge" class with a mode; modern: one class per op.
_MERGE_MODES = {"sum": "add", "add": "add", "mul": "product",
                "ave": "average", "average": "average", "max": "max",
                "sub": "subtract", "subtract": "subtract"}
_MERGE_CLASSES = {"Add": "add", "Multiply": "product", "Average": "average",
                  "Maximum": "max", "Subtract": "subtract"}


def _map_merge(cls, cfg):
    """Returns a GraphVertexConf for merge-style layers, else None."""
    from ..nn.conf.graph_vertices import ElementWiseVertex, MergeVertex
    if cls == "Merge":   # Keras 1
        mode = cfg.get("mode", "sum")
        if mode in ("concat", "concatenate"):
            return MergeVertex()
        if mode in _MERGE_MODES:
            return ElementWiseVertex(op=_MERGE_MODES[mode])
        raise ValueError(f"Unsupported Keras Merge mode '{mode}'")
    if cls in _MERGE_CLASSES:
        return ElementWiseVertex(op=_MERGE_CLASSES[cls])
    if cls == "Concatenate":
        return MergeVertex()
    return None


# ---------------------------------------------------------------------------
# Functional Model -> ComputationGraph build
# reference: KerasModel.java:57 (getComputationGraphConfiguration +
# getComputationGraph)
# ---------------------------------------------------------------------------

def _inbound_names(lc):
    """Names of the layers feeding `lc`, across config dialects.

    Keras 1/2 classic: inbound_nodes = [[[name, node_idx, tensor_idx, ...],
    ...]]; modern Keras: inbound_nodes = [{"args": [tensor-or-list], ...}]
    with __keras_tensor__ dicts carrying keras_history = [name, ...]."""
    nodes = lc.get("inbound_nodes", [])
    if not nodes:
        return []
    node = nodes[0]
    names = []
    if isinstance(node, dict):                      # modern dialect
        def collect(obj):
            if isinstance(obj, dict):
                if obj.get("class_name") == "__keras_tensor__":
                    names.append(obj["config"]["keras_history"][0])
                else:
                    for v in obj.values():
                        collect(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    collect(v)
        collect(node.get("args", []))
        return names
    for entry in node:                              # classic dialect
        names.append(entry[0])
    return names


def _keras_input_type(cfg, dim_ordering):
    shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
    if shape is None:
        raise ValueError("InputLayer has no batch_input_shape/batch_shape")
    dims = list(shape[1:])
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return InputType.recurrent(dims[1])
    if len(dims) == 3:
        if dim_ordering in ("th", "channels_first"):
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(h, w, c)
    raise ValueError(f"Unsupported input shape {shape}")


def _ref_names(refs):
    """[[name, 0, 0], ...] or [name, 0, 0] -> [name, ...]"""
    if refs and isinstance(refs[0], str):
        refs = [refs]
    return [r[0] for r in refs]


def _build_functional(model_cfg, weights):
    """Functional Model JSON -> ComputationGraph (+ weight copy).

    Structural Flatten/Reshape layers dissolve into name aliases (the
    GraphBuilder auto-inserts CnnToFeedForward preprocessors); Merge-family
    layers become MergeVertex/ElementWiseVertex; a network-output Dense with
    a softmax/sigmoid activation becomes an OutputLayer so the imported
    graph is trainable via fit() (the reference's enforceTrainingConfig
    behavior)."""
    cls_name = model_cfg.get("class_name")
    if cls_name not in ("Model", "Functional"):
        raise ValueError(f"Expected functional Model, got {cls_name}")
    cfg = model_cfg["config"]
    layer_cfgs = cfg["layers"]
    output_names = set(_ref_names(cfg.get("output_layers", [])))

    dim_ordering = "tf"
    for lc in layer_cfgs:
        do = lc["config"].get("dim_ordering") or lc["config"].get("data_format")
        if do:
            dim_ordering = do
            break

    gb = (NeuralNetConfiguration.Builder().seed(12345).graph_builder())
    alias = {}            # keras name -> vertex/input name it resolves to
    input_types = []
    input_names = []
    mapped = []           # (vertex_name, our LayerConf, keras cfg)
    dense_after_flatten = set()
    flatten_sources = set()

    for lc in layer_cfgs:
        cls = lc["class_name"]
        kcfg = lc["config"]
        name = kcfg.get("name") or lc.get("name")
        inputs = [alias.get(n, n) for n in _inbound_names(lc)]
        if cls == "InputLayer":
            input_names.append(name)
            input_types.append(_keras_input_type(kcfg, dim_ordering))
            gb.add_inputs(name)
            alias[name] = name
            continue
        merge = _map_merge(cls, kcfg)
        if merge is not None:
            gb.add_vertex(name, merge, *inputs)
            alias[name] = name
            continue
        layer, structural = _map_layer(cls, kcfg, dim_ordering)
        if structural or layer is None:
            # Flatten/Reshape dissolve: downstream preprocessor inference
            # reproduces the shape change
            alias[name] = inputs[0]
            if cls == "Flatten":
                flatten_sources.add(inputs[0])
            continue
        if (name in output_names and isinstance(layer, DenseLayer)
                and layer.activation in ("softmax", "sigmoid")):
            loss = "mcxent" if layer.activation == "softmax" else "xent"
            layer = OutputLayer(n_out=layer.n_out,
                                activation=layer.activation,
                                loss_function=loss)
        if (isinstance(layer, (DenseLayer, OutputLayer))
                and inputs and inputs[0] in flatten_sources
                and dim_ordering in ("th", "channels_first")):
            dense_after_flatten.add(name)
        gb.add_layer(name, layer, *inputs)
        alias[name] = name
        mapped.append((name, layer, lc))

    gb.set_outputs(*[alias.get(n, n)
                     for n in _ref_names(cfg.get("output_layers", []))])
    in_order = _ref_names(cfg.get("input_layers", [])) or input_names
    gb.set_input_types(*[input_types[input_names.index(n)]
                         for n in in_order])
    graph_conf = gb.build()
    from ..nn.graph import ComputationGraph
    net = ComputationGraph(graph_conf).init()
    if weights is not None:
        _copy_weights_graph(net, mapped, weights, dense_after_flatten,
                            graph_conf)
    return net


def _copy_weights_graph(net, mapped, weights, dense_after_flatten, conf):
    """Copy Keras weight arrays into the ComputationGraph's name-keyed param
    pytree. reference: KerasModel.copyWeights."""
    import jax.numpy as jnp

    params = {n: dict(p) for n, p in net._params.items()}
    state = {n: (dict(s) if isinstance(s, dict) else s)
             for n, s in net._model_state.items()}
    types = getattr(conf, "vertex_output_types", {})
    for name, layer, lc in mapped:
        cls = lc["class_name"]
        w = weights.get(name, [])
        if not w:
            continue
        if cls == "Dense":
            W, b = w[0], w[1]
            if name in dense_after_flatten:
                # rows are CHW-ordered (channels-first flatten); ours HWC
                src = conf.vertices[name].inputs[0]
                t = types.get(src)
                if t is not None and hasattr(t, "channels"):
                    c, h, ww = t.channels, t.height, t.width
                    W = (W.reshape(c, h, ww, -1).transpose(1, 2, 0, 3)
                         .reshape(c * h * ww, -1))
            params[name]["W"] = jnp.asarray(W)
            params[name]["b"] = jnp.asarray(np.asarray(b).ravel())
        elif cls in ("Convolution2D", "Conv2D"):
            W = w[0]
            do = lc["config"].get("dim_ordering") or \
                lc["config"].get("data_format")
            th = (do in ("th", "channels_first") if do is not None
                  else (W.shape[0] == layer.n_out
                        and W.shape[-1] != layer.n_out))
            if th:
                W = W.transpose(2, 3, 1, 0)   # OIHW -> HWIO
            params[name]["W"] = jnp.asarray(W)
            if len(w) > 1:                    # use_bias=False: kernel only
                params[name]["b"] = jnp.asarray(np.asarray(w[1]).ravel())
        elif cls == "LSTM":
            if len(w) == 12:   # Keras 1: per-gate i,c,f,o triplets
                (Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo) = w
                W = np.concatenate([Wc, Wi, Wf, Wo], axis=1)
                RW = np.concatenate([Uc, Ui, Uf, Uo], axis=1)
                b = np.concatenate([bc, bi, bf, bo])
            else:              # modern: fused kernels, gate order i,f,c,o
                K, R, b4 = w[0], w[1], w[2]
                H = K.shape[1] // 4
                def regate(a, axis):
                    i, f, c, o = np.split(a, 4, axis=axis)
                    return np.concatenate([c, i, f, o], axis=axis)
                W, RW, b = regate(K, 1), regate(R, 1), regate(b4, 0)
            params[name]["W"] = jnp.asarray(W)
            params[name]["RW"] = jnp.asarray(RW)
            params[name]["b"] = jnp.asarray(b)
        elif cls == "Embedding":
            params[name]["W"] = jnp.asarray(w[0])
            params[name]["b"] = jnp.zeros((layer.n_out,), jnp.float32)
        elif cls == "BatchNormalization":
            gamma, beta, mean, var = w[0], w[1], w[2], w[3]
            params[name]["gamma"] = jnp.asarray(gamma)
            params[name]["beta"] = jnp.asarray(beta)
            state[name] = {"mean": jnp.asarray(mean),
                           "var": jnp.asarray(np.abs(var))}
    net._params = params
    net._model_state = state


def _copy_weights(net, mapped, weights, flatten_perm, conf):
    """Copy Keras weight arrays into the net's param pytree.
    reference: KerasModel.copyWeights (name mapping KerasModel.java:76-99)."""
    import jax.numpy as jnp

    our_idx = 0
    params = [dict(p) for p in net._params]
    state = [dict(s) for s in net._model_state]
    prev_cnn_shape = None   # (C,H,W) before the most recent Flatten (th)
    cur_type = conf.input_type
    for layer, lc in mapped:
        cls = lc["class_name"]
        name = lc["config"].get("name", "")
        if layer is None:
            if cls == "Flatten":
                from ..nn.conf.input_type import ConvolutionalInputType
                if isinstance(cur_type, ConvolutionalInputType):
                    prev_cnn_shape = (cur_type.channels, cur_type.height,
                                      cur_type.width)
            continue
        w = weights.get(name, [])
        if cls == "Dense" and w:
            W, b = w[0], w[1]
            if our_idx in flatten_perm and prev_cnn_shape is not None:
                c, h, hw = prev_cnn_shape
                # rows are CHW-ordered (th flatten); ours flatten HWC
                W = (W.reshape(c, h, hw, -1).transpose(1, 2, 0, 3)
                     .reshape(c * h * hw, -1))
            params[our_idx]["W"] = jnp.asarray(W)
            params[our_idx]["b"] = jnp.asarray(b.ravel())
        elif cls in ("Convolution2D", "Conv2D") and w:
            W = w[0]
            # th stores OIHW; we are HWIO-native (tf ordering matches).
            # Trust the layer's dim_ordering; fall back to a shape check
            # when it is absent (square kernels can be ambiguous).
            do = lc["config"].get("dim_ordering")
            th = (do == "th" if do is not None
                  else (W.shape[0] == layer.n_out
                        and W.shape[-1] != layer.n_out))
            if th:
                W = W.transpose(2, 3, 1, 0)
            params[our_idx]["W"] = jnp.asarray(W)
            if len(w) > 1:                    # use_bias=False: kernel only
                params[our_idx]["b"] = jnp.asarray(w[1].ravel())
        elif cls == "LSTM" and w:
            # Keras 1 order: W_i U_i b_i, W_c U_c b_c, W_f U_f b_f, W_o U_o b_o
            (Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo) = w
            # our gate order: a(=c), i, f, o
            W = np.concatenate([Wc, Wi, Wf, Wo], axis=1)
            RW = np.concatenate([Uc, Ui, Uf, Uo], axis=1)
            b = np.concatenate([bc, bi, bf, bo])
            params[our_idx]["W"] = jnp.asarray(W)
            params[our_idx]["RW"] = jnp.asarray(RW)
            params[our_idx]["b"] = jnp.asarray(b)
            # peepholes stay zero (Keras LSTM has none)
        elif cls == "Embedding" and w:
            params[our_idx]["W"] = jnp.asarray(w[0])
            params[our_idx]["b"] = jnp.zeros((layer.n_out,), jnp.float32)
        elif cls == "BatchNormalization" and w:
            gamma, beta, mean, var = w[0], w[1], w[2], w[3]
            params[our_idx]["gamma"] = jnp.asarray(gamma)
            params[our_idx]["beta"] = jnp.asarray(beta)
            state[our_idx] = {"mean": jnp.asarray(mean),
                              "var": jnp.asarray(np.abs(var))}
        cur_type = layer.get_output_type(cur_type) if layer else cur_type
        our_idx += 1
    net._params = params
    net._model_state = state


def dim_ordering_of(lc):
    return lc["config"].get("dim_ordering", "tf")
