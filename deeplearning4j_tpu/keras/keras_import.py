"""Keras 1.x model import (JSON topology + HDF5 weights).

TPU-native equivalent of reference deeplearning4j-modelimport:
KerasModelImport (KerasModelImport.java:85-250), KerasModel/
KerasSequentialModel (KerasModel.java:57), per-layer mapping (KerasLayer.java,
1,111 LoC). The reference reads HDF5 through JavaCPP; here h5py plays that
role.

Supported layers (the reference's set, KerasLayer.java): Dense,
Convolution2D, MaxPooling2D, AveragePooling2D, LSTM, Embedding,
BatchNormalization, Activation, Dropout, Flatten, Reshape, ZeroPadding2D,
Merge (sequential path treats structural layers as preprocessor hints).

Dim-ordering: Keras 1 'th' (NCHW) and 'tf' (NHWC) are both handled; since
this framework is NHWC-native, 'th' conv kernels are transposed
OIHW -> HWIO and the first post-Flatten Dense has its rows permuted from
CHW to HWC order (the reference does the same NCHW bookkeeping in
KerasModel.copyWeights).
"""
from __future__ import annotations

import json

import numpy as np

from ..nn.conf.input_type import InputType
from ..nn.conf.layers import (ActivationLayer, BatchNormalization,
                              ConvolutionLayer, DenseLayer, DropoutLayer,
                              EmbeddingLayer, GravesLSTM, LossLayer,
                              OutputLayer, SubsamplingLayer, ZeroPaddingLayer)
from ..nn.conf.neural_net_configuration import NeuralNetConfiguration

_ACTIVATION_MAP = {
    "linear": "identity", "relu": "relu", "tanh": "tanh",
    "sigmoid": "sigmoid", "softmax": "softmax", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
}


def _map_activation(name):
    if name not in _ACTIVATION_MAP:
        raise ValueError(f"Unsupported Keras activation '{name}'")
    return _ACTIVATION_MAP[name]


# ---------------------------------------------------------------------------
# Public API — reference KerasModelImport.java
# ---------------------------------------------------------------------------

def import_keras_sequential_model_and_weights(h5_path):
    """Read a Keras 1.x sequential model saved via model.save(): topology from
    the `model_config` attribute, weights from `model_weights`.
    reference: KerasModelImport.importKerasSequentialModelAndWeights."""
    import h5py
    with h5py.File(h5_path, "r") as f:
        cfg = f.attrs["model_config"]
        if isinstance(cfg, bytes):
            cfg = cfg.decode("utf-8")
        model_cfg = json.loads(cfg)
        weights = _read_weight_groups(f["model_weights"]
                                      if "model_weights" in f else f)
    return _build_sequential(model_cfg, weights)


importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights


def import_keras_model_configuration(json_path_or_str):
    """Topology-only import (no weights).
    reference: KerasModelImport.importKerasModelConfiguration."""
    s = json_path_or_str
    if not s.lstrip().startswith("{"):
        with open(s, "r", encoding="utf-8") as fh:
            s = fh.read()
    model_cfg = json.loads(s)
    return _build_sequential(model_cfg, weights=None, conf_only=True)


importKerasModelConfiguration = import_keras_model_configuration


def _read_weight_groups(g):
    """layer-name -> list of arrays, in `weight_names` attribute order."""
    out = {}
    for lname in g:
        grp = g[lname]
        if "weight_names" in grp.attrs:
            names = [n.decode() if isinstance(n, bytes) else n
                     for n in grp.attrs["weight_names"]]
            out[lname] = [np.asarray(grp[n]) for n in names]
        else:
            out[lname] = [np.asarray(grp[d]) for d in sorted(grp)]
    return out


# ---------------------------------------------------------------------------
# Sequential build
# ---------------------------------------------------------------------------

def _build_sequential(model_cfg, weights, conf_only=False):
    if model_cfg.get("class_name") != "Sequential":
        raise ValueError(
            f"Expected Sequential model, got {model_cfg.get('class_name')} "
            "(functional Model import: use the ComputationGraph path)")
    layer_cfgs = model_cfg["config"]
    if isinstance(layer_cfgs, dict):   # keras 2 style {"layers": [...]}
        layer_cfgs = layer_cfgs["layers"]

    builder = (NeuralNetConfiguration.Builder().seed(12345).list())
    input_type, dim_ordering = _input_type_of(layer_cfgs[0])

    mapped = []        # (our LayerConf or None, keras cfg)
    flatten_perm = []  # indices of our-layers needing th->HWC row permute
    pending_flatten_shape = None
    idx = 0
    for lc in layer_cfgs:
        cls = lc["class_name"]
        cfg = lc["config"]
        layer, is_structural = _map_layer(cls, cfg, dim_ordering)
        if cls == "Flatten":
            pending_flatten_shape = "flatten"
            mapped.append((None, lc))
            continue
        if layer is None:
            mapped.append((None, lc))
            continue
        if (pending_flatten_shape and isinstance(layer, DenseLayer)
                and dim_ordering == "th"):
            flatten_perm.append(idx)
        pending_flatten_shape = None
        builder.layer(idx, layer)
        mapped.append((layer, lc))
        idx += 1

    builder.set_input_type(input_type)
    conf = builder.build()
    from ..nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(conf)
    if conf_only:
        return conf
    net.init()
    if weights is not None:
        _copy_weights(net, mapped, weights, flatten_perm, conf)
    return net


def _input_type_of(first_layer_cfg):
    cfg = first_layer_cfg["config"]
    shape = cfg.get("batch_input_shape")
    dim_ordering = cfg.get("dim_ordering", "tf")
    if shape is None:
        raise ValueError("First layer has no batch_input_shape")
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0]), dim_ordering
    if len(dims) == 2:
        return InputType.recurrent(dims[1]), dim_ordering
    if len(dims) == 3:
        if dim_ordering == "th":   # (C, H, W)
            c, h, w = dims
        else:                      # (H, W, C)
            h, w, c = dims
        return InputType.convolutional(h, w, c), dim_ordering
    raise ValueError(f"Unsupported input shape {shape}")


def _map_layer(cls, cfg, dim_ordering):
    """Keras layer config -> our LayerConf (or None for structural layers).
    reference: KerasLayer layer-by-layer mapping."""
    act = cfg.get("activation", "linear")
    if cls == "Dense":
        return DenseLayer(n_out=int(cfg["output_dim"]),
                          activation=_map_activation(act)), False
    if cls in ("Convolution2D", "Conv2D"):
        return ConvolutionLayer(
            n_out=int(cfg["nb_filter"]),
            kernel_size=(int(cfg["nb_row"]), int(cfg["nb_col"])),
            stride=tuple(cfg.get("subsample", (1, 1))),
            convolution_mode=("same" if cfg.get("border_mode") == "same"
                              else "truncate"),
            activation=_map_activation(act)), False
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            pooling_type="max" if cls.startswith("Max") else "avg",
            kernel_size=tuple(cfg.get("pool_size", (2, 2))),
            stride=tuple(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode=("same" if cfg.get("border_mode") == "same"
                              else "truncate")), False
    if cls == "LSTM":
        return GravesLSTM(n_out=int(cfg["output_dim"]),
                          activation=_map_activation(act),
                          gate_activation=_map_activation(
                              cfg.get("inner_activation", "hard_sigmoid")),
                          forget_gate_bias_init=0.0), False
    if cls == "Embedding":
        return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                              n_out=int(cfg["output_dim"]),
                              activation="identity"), False
    if cls == "BatchNormalization":
        return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                  decay=float(cfg.get("momentum", 0.99))), False
    if cls == "Activation":
        return ActivationLayer(activation=_map_activation(act)), False
    if cls == "Dropout":
        # Keras p = drop probability; ours = retain probability
        return DropoutLayer(dropout=1.0 - float(cfg.get("p", 0.5))), False
    if cls == "ZeroPadding2D":
        return ZeroPaddingLayer(pad=tuple(cfg.get("padding", (1, 1)))), False
    if cls in ("Flatten", "Reshape", "InputLayer"):
        return None, True
    raise ValueError(f"Unsupported Keras layer type '{cls}'")


def _copy_weights(net, mapped, weights, flatten_perm, conf):
    """Copy Keras weight arrays into the net's param pytree.
    reference: KerasModel.copyWeights (name mapping KerasModel.java:76-99)."""
    import jax.numpy as jnp

    our_idx = 0
    params = [dict(p) for p in net._params]
    state = [dict(s) for s in net._model_state]
    prev_cnn_shape = None   # (C,H,W) before the most recent Flatten (th)
    cur_type = conf.input_type
    for layer, lc in mapped:
        cls = lc["class_name"]
        name = lc["config"].get("name", "")
        if layer is None:
            if cls == "Flatten":
                from ..nn.conf.input_type import ConvolutionalInputType
                if isinstance(cur_type, ConvolutionalInputType):
                    prev_cnn_shape = (cur_type.channels, cur_type.height,
                                      cur_type.width)
            continue
        w = weights.get(name, [])
        if cls == "Dense" and w:
            W, b = w[0], w[1]
            if our_idx in flatten_perm and prev_cnn_shape is not None:
                c, h, hw = prev_cnn_shape
                # rows are CHW-ordered (th flatten); ours flatten HWC
                W = (W.reshape(c, h, hw, -1).transpose(1, 2, 0, 3)
                     .reshape(c * h * hw, -1))
            params[our_idx]["W"] = jnp.asarray(W)
            params[our_idx]["b"] = jnp.asarray(b.ravel())
        elif cls in ("Convolution2D", "Conv2D") and w:
            W, b = w[0], w[1]
            # th stores OIHW; we are HWIO-native (tf ordering matches).
            # Trust the layer's dim_ordering; fall back to a shape check
            # when it is absent (square kernels can be ambiguous).
            do = lc["config"].get("dim_ordering")
            th = (do == "th" if do is not None
                  else (W.shape[0] == layer.n_out
                        and W.shape[-1] != layer.n_out))
            if th:
                W = W.transpose(2, 3, 1, 0)
            params[our_idx]["W"] = jnp.asarray(W)
            params[our_idx]["b"] = jnp.asarray(b.ravel())
        elif cls == "LSTM" and w:
            # Keras 1 order: W_i U_i b_i, W_c U_c b_c, W_f U_f b_f, W_o U_o b_o
            (Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo) = w
            # our gate order: a(=c), i, f, o
            W = np.concatenate([Wc, Wi, Wf, Wo], axis=1)
            RW = np.concatenate([Uc, Ui, Uf, Uo], axis=1)
            b = np.concatenate([bc, bi, bf, bo])
            params[our_idx]["W"] = jnp.asarray(W)
            params[our_idx]["RW"] = jnp.asarray(RW)
            params[our_idx]["b"] = jnp.asarray(b)
            # peepholes stay zero (Keras LSTM has none)
        elif cls == "Embedding" and w:
            params[our_idx]["W"] = jnp.asarray(w[0])
            params[our_idx]["b"] = jnp.zeros((layer.n_out,), jnp.float32)
        elif cls == "BatchNormalization" and w:
            gamma, beta, mean, var = w[0], w[1], w[2], w[3]
            params[our_idx]["gamma"] = jnp.asarray(gamma)
            params[our_idx]["beta"] = jnp.asarray(beta)
            state[our_idx] = {"mean": jnp.asarray(mean),
                              "var": jnp.asarray(np.abs(var))}
        cur_type = layer.get_output_type(cur_type) if layer else cur_type
        our_idx += 1
    net._params = params
    net._model_state = state


def dim_ordering_of(lc):
    return lc["config"].get("dim_ordering", "tf")
