from .tsne import BarnesHutTsne, Tsne

__all__ = ["BarnesHutTsne", "Tsne"]
