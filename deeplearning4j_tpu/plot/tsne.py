"""t-SNE embedding for visualization.

TPU-native equivalent of reference deeplearning4j-core plot/BarnesHutTsne.java
+ plot/Tsne.java (1,276 LoC). Redesign rationale: the reference's Barnes-Hut
quadtree exists to avoid an O(N^2) host loop; on TPU the dense [N,N]
similarity and gradient kernels ARE the fast path (matmuls + fused
elementwise on the MXU), so the whole gradient loop is one jitted
`lax.fori_loop` — exact t-SNE, no tree approximation, same API (fit ->
2-D/3-D coordinates).

Standard recipe: perplexity binary search for conditional P, symmetrize,
early exaggeration, momentum gradient descent.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _cond_probs(x, perplexity, tol=1e-5, max_tries=50):
    """Binary-search per-point Gaussian bandwidths to hit the target
    perplexity (host-side, as in the reference's computeGaussianPerplexity)."""
    n = x.shape[0]
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    P = np.zeros((n, n))
    log_u = np.log(perplexity)
    for i in range(n):
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        di = np.delete(d2[i], i)
        for _ in range(max_tries):
            p = np.exp(-di * beta)
            s = max(p.sum(), 1e-12)
            h = np.log(s) + beta * (di * p).sum() / s
            if abs(h - log_u) < tol:
                break
            if h > log_u:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        p = np.exp(-di * beta)
        p /= max(p.sum(), 1e-12)
        P[i, np.arange(n) != i] = p
    P = (P + P.T) / (2 * n)
    return np.maximum(P, 1e-12)


@functools.partial(jax.jit, static_argnums=(3,))
def _tsne_loop(P, y0, key, n_iter, momentum=0.8, lr=200.0,
               exaggeration=12.0, exaggeration_iters=100):
    """The full gradient-descent loop as ONE compiled program."""
    n = y0.shape[0]

    def grad_kl(y, Pe):
        d2 = (jnp.sum(y * y, 1)[:, None] - 2 * y @ y.T
              + jnp.sum(y * y, 1)[None, :])
        num = 1.0 / (1.0 + d2)
        num = num.at[jnp.arange(n), jnp.arange(n)].set(0.0)
        Q = num / jnp.maximum(jnp.sum(num), 1e-12)
        Q = jnp.maximum(Q, 1e-12)
        PQ = (Pe - Q) * num
        g = 4.0 * ((jnp.diag(jnp.sum(PQ, 1)) - PQ) @ y)
        return g

    def body(i, carry):
        y, v = carry
        Pe = jnp.where(i < exaggeration_iters, P * exaggeration, P)
        g = grad_kl(y, Pe)
        v = momentum * v - lr * g
        y = y + v
        y = y - jnp.mean(y, axis=0)
        return y, v

    y, _ = jax.lax.fori_loop(0, n_iter, body, (y0, jnp.zeros_like(y0)))
    return y


class Tsne:
    """reference API: plot/Tsne.java + BarnesHutTsne.Builder."""

    class Builder:
        def __init__(self):
            self._kw = {}

        def set_max_iter(self, v):
            self._kw["max_iter"] = int(v); return self

        setMaxIter = set_max_iter

        def perplexity(self, v):
            self._kw["perplexity"] = float(v); return self

        def theta(self, v):
            return self   # Barnes-Hut approximation knob: exact kernel here

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v); return self

        learningRate = learning_rate

        def num_dimension(self, v):
            self._kw["n_components"] = int(v); return self

        numDimension = num_dimension

        def seed(self, v):
            self._kw["seed"] = int(v); return self

        def build(self):
            return Tsne(**self._kw)

    def __init__(self, n_components=2, perplexity=30.0, max_iter=500,
                 learning_rate=200.0, seed=123):
        self.n_components = int(n_components)
        self.perplexity = float(perplexity)
        self.max_iter = int(max_iter)
        self.learning_rate = float(learning_rate)
        self.seed = int(seed)
        self.embedding = None

    def fit(self, x):
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        perp = min(self.perplexity, (n - 1) / 3.0)
        P = jnp.asarray(_cond_probs(x, perp), jnp.float32)
        key = jax.random.PRNGKey(self.seed)
        y0 = 1e-2 * jax.random.normal(key, (n, self.n_components),
                                      jnp.float32)
        y = _tsne_loop(P, y0, key, self.max_iter,
                       lr=self.learning_rate)
        self.embedding = np.asarray(y)
        return self.embedding

    fit_transform = fit

    def plot(self, x, labels=None, path=None):
        """Fit and dump coordinates (+labels) to a TSV like the reference's
        saveCoordsForPlot."""
        coords = self.fit(x)
        if path:
            with open(path, "w", encoding="utf-8") as fh:
                for i, row in enumerate(coords):
                    lab = labels[i] if labels is not None else i
                    fh.write("\t".join(f"{v:.6f}" for v in row)
                             + f"\t{lab}\n")
        return coords


BarnesHutTsne = Tsne   # exact kernel; alias keeps the reference's class name
