"""t-SNE embedding for visualization.

TPU-native equivalent of reference deeplearning4j-core plot/BarnesHutTsne.java
+ plot/Tsne.java + clustering/sptree (1,276 LoC). Two paths:

* dense (small/medium N): the [N,N] similarity and gradient kernels ARE
  the TPU fast path (matmuls + fused elementwise on the MXU); the whole
  gradient loop is one jitted `lax.fori_loop` — exact t-SNE, no tree.
* barnes_hut (N up to 50k+): the reference's O(N log N) design, kept where
  the reference keeps it — on the host. kNN candidate search and the
  per-point perplexity bisection are VECTORIZED in JAX (every point
  searched in parallel — the reference's computeGaussianPerplexity row
  loop collapsed to a scan); the quadtree build + theta-criterion
  repulsion and CSR sparse attraction run in the native C++ runtime
  (`native/dl4j_tpu_native.cpp dl4j_bh_repulsion/dl4j_bh_attraction`,
  threaded), with exact numpy fallbacks when the toolchain is missing.

`method="auto"` picks dense below _DENSE_MAX points, barnes_hut above.
Standard recipe either way: perplexity search for conditional P,
symmetrize, early exaggeration, momentum + adaptive gains descent.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _cond_probs(x, perplexity, tol=1e-5, max_tries=50):
    """Binary-search per-point Gaussian bandwidths to hit the target
    perplexity (host-side, as in the reference's computeGaussianPerplexity)."""
    n = x.shape[0]
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    P = np.zeros((n, n))
    log_u = np.log(perplexity)
    for i in range(n):
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        di = np.delete(d2[i], i)
        for _ in range(max_tries):
            p = np.exp(-di * beta)
            s = max(p.sum(), 1e-12)
            h = np.log(s) + beta * (di * p).sum() / s
            if abs(h - log_u) < tol:
                break
            if h > log_u:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        p = np.exp(-di * beta)
        p /= max(p.sum(), 1e-12)
        P[i, np.arange(n) != i] = p
    P = (P + P.T) / (2 * n)
    return np.maximum(P, 1e-12)


@functools.partial(jax.jit, static_argnums=(3,))
def _tsne_loop(P, y0, key, n_iter, momentum=0.8, lr=200.0,
               exaggeration=12.0, exaggeration_iters=100):
    """The full gradient-descent loop as ONE compiled program."""
    n = y0.shape[0]

    def grad_kl(y, Pe):
        d2 = (jnp.sum(y * y, 1)[:, None] - 2 * y @ y.T
              + jnp.sum(y * y, 1)[None, :])
        num = 1.0 / (1.0 + d2)
        num = num.at[jnp.arange(n), jnp.arange(n)].set(0.0)
        Q = num / jnp.maximum(jnp.sum(num), 1e-12)
        Q = jnp.maximum(Q, 1e-12)
        PQ = (Pe - Q) * num
        g = 4.0 * ((jnp.diag(jnp.sum(PQ, 1)) - PQ) @ y)
        return g

    def body(i, carry):
        y, v = carry
        Pe = jnp.where(i < exaggeration_iters, P * exaggeration, P)
        g = grad_kl(y, Pe)
        v = momentum * v - lr * g
        y = y + v
        y = y - jnp.mean(y, axis=0)
        return y, v

    y, _ = jax.lax.fori_loop(0, n_iter, body, (y0, jnp.zeros_like(y0)))
    return y


_DENSE_MAX = 4096      # auto: dense TPU kernel up to here, Barnes-Hut above


@functools.partial(jax.jit, static_argnums=(2,))
def _knn_chunk(xq, x, k):
    """Squared distances + indices of the k+1 nearest points (self
    included) for a chunk of queries — one MXU matmul per chunk."""
    d2 = (jnp.sum(xq * xq, 1)[:, None] - 2.0 * xq @ x.T
          + jnp.sum(x * x, 1)[None, :])
    neg, idx = jax.lax.top_k(-d2, k + 1)
    return -neg, idx


@jax.jit
def _beta_search_rows(d2, log_u):
    """Vectorized perplexity bisection: all points' bandwidths at once
    (the reference's computeGaussianPerplexity per-row loop, collapsed to
    one 50-step scan over [N] betas). d2: [N, K] neighbor sq-distances."""
    n = d2.shape[0]

    def body(carry, _):
        beta, lo, hi = carry
        p = jnp.exp(-d2 * beta[:, None])
        s = jnp.maximum(p.sum(1), 1e-12)
        h = jnp.log(s) + beta * (d2 * p).sum(1) / s
        too_high = h > log_u            # entropy too high -> raise beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(hi), beta * 2.0, 0.5 * (beta + hi)),
            jnp.where(lo > 0.0, 0.5 * (beta + lo), beta * 0.5))
        return (beta, lo, hi), None

    init = (jnp.ones(n, d2.dtype), jnp.zeros(n, d2.dtype),
            jnp.full(n, jnp.inf, d2.dtype))
    (beta, _, _), _ = jax.lax.scan(body, init, None, length=50)
    p = jnp.exp(-d2 * beta[:, None])
    return p / jnp.maximum(p.sum(1, keepdims=True), 1e-12)


def _sparse_sym_p(x, perplexity, chunk=1024):
    """kNN conditional P, symmetrized to CSR (row_ptr, cols, vals)."""
    n = x.shape[0]
    k = max(3, min(n - 1, int(3 * perplexity)))
    xj = jnp.asarray(x, jnp.float32)
    d2s, idxs = [], []
    for s in range(0, n, chunk):
        d2c, idxc = _knn_chunk(xj[s:s + chunk], xj, k)
        d2s.append(np.asarray(d2c))
        idxs.append(np.asarray(idxc))
    d2 = np.concatenate(d2s)                        # [n, k+1] ascending
    idx = np.concatenate(idxs)
    # drop self (first occurrence of the query's own index per row); with
    # >k exact duplicates the self index can be tied out of the top-(k+1),
    # making argmax return 0 — drop the farthest column for those rows
    # instead of silently discarding the true nearest neighbor
    rows_arange = np.arange(n)
    is_self = idx == rows_arange[:, None]
    self_pos = np.where(is_self.any(1), np.argmax(is_self, 1), idx.shape[1] - 1)
    keep = np.ones_like(idx, bool)
    keep[rows_arange, self_pos] = False
    d2 = d2[keep].reshape(n, k)
    idx = idx[keep].reshape(n, k)
    p = np.asarray(_beta_search_rows(jnp.asarray(d2, jnp.float32),
                                     float(np.log(perplexity))))
    # symmetrize: P_sym = (P + P^T) / (2n) over the union pattern
    rows = np.repeat(rows_arange, k).astype(np.int64)
    cols = idx.ravel().astype(np.int64)
    keys = np.concatenate([rows * n + cols, cols * n + rows])
    vals = np.concatenate([p.ravel(), p.ravel()]).astype(np.float64)
    uk, inv = np.unique(keys, return_inverse=True)
    sv = np.zeros(uk.shape[0])
    np.add.at(sv, inv, vals)
    sv /= (2.0 * n)
    r, c = (uk // n).astype(np.int64), (uk % n).astype(np.int32)
    row_ptr = np.searchsorted(r, np.arange(n + 1), side="left").astype(
        np.int64)
    return row_ptr, c, np.maximum(sv, 1e-12).astype(np.float32)


def _np_attraction(y, row_ptr, cols, vals):
    """Exact numpy fallback for dl4j_bh_attraction (COO vectorized)."""
    n = y.shape[0]
    rows = np.repeat(np.arange(n), np.diff(row_ptr))
    d = y[rows] - y[cols]
    q = 1.0 / (1.0 + (d * d).sum(1))
    w = (vals * q)[:, None] * d
    out = np.zeros_like(y)
    np.add.at(out, rows, w)
    return out


def _np_repulsion(y, chunk=2048):
    """Exact (theta=0) chunked fallback for dl4j_bh_repulsion."""
    n = y.shape[0]
    rep = np.zeros_like(y)
    Z = 0.0
    for s in range(0, n, chunk):
        d = y[s:s + chunk, None, :] - y[None, :, :]
        q = 1.0 / (1.0 + (d * d).sum(-1))
        q[np.arange(s, min(s + chunk, n)) - s,
          np.arange(s, min(s + chunk, n))] = 0.0
        Z += q.sum()
        rep[s:s + chunk] = ((q * q)[..., None] * d).sum(1)
    return rep, max(Z, 1e-12)


class Tsne:
    """reference API: plot/Tsne.java + BarnesHutTsne.Builder."""

    class Builder:
        def __init__(self):
            self._kw = {}

        def set_max_iter(self, v):
            self._kw["max_iter"] = int(v); return self

        setMaxIter = set_max_iter

        def perplexity(self, v):
            self._kw["perplexity"] = float(v); return self

        def theta(self, v):
            self._kw["theta"] = float(v); return self

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v); return self

        learningRate = learning_rate

        def num_dimension(self, v):
            self._kw["n_components"] = int(v); return self

        numDimension = num_dimension

        def seed(self, v):
            self._kw["seed"] = int(v); return self

        def use_barnes_hut(self, v):
            self._kw["method"] = "barnes_hut" if v else "dense"
            return self

        def build(self):
            return Tsne(**self._kw)

    def __init__(self, n_components=2, perplexity=30.0, max_iter=500,
                 learning_rate=200.0, seed=123, theta=0.5, method="auto"):
        self.n_components = int(n_components)
        self.perplexity = float(perplexity)
        self.max_iter = int(max_iter)
        self.learning_rate = float(learning_rate)
        self.seed = int(seed)
        self.theta = float(theta)
        self.method = method
        self.embedding = None

    def fit(self, x):
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        method = self.method
        if method == "auto":
            # the quadtree is 2-D; 3-D embeddings stay on the exact path
            method = ("dense" if n <= _DENSE_MAX or self.n_components != 2
                      else "barnes_hut")
        if method == "barnes_hut":
            return self._fit_barnes_hut(x)
        perp = min(self.perplexity, (n - 1) / 3.0)
        P = jnp.asarray(_cond_probs(x, perp), jnp.float32)
        key = jax.random.PRNGKey(self.seed)
        y0 = 1e-2 * jax.random.normal(key, (n, self.n_components),
                                      jnp.float32)
        y = _tsne_loop(P, y0, key, self.max_iter,
                       lr=self.learning_rate)
        self.embedding = np.asarray(y)
        return self.embedding

    fit_transform = fit

    def _fit_barnes_hut(self, x):
        """O(N log N) path (reference BarnesHutTsne.gradient + SpTree):
        sparse kNN attraction + quadtree repulsion, momentum + adaptive
        gains (the reference's gains.muli / learning-rate schedule)."""
        if self.n_components != 2:
            raise ValueError("barnes_hut t-SNE is 2-D (quadtree), like "
                             "the reference's BarnesHutTsne")
        from ..common import native_ops
        n = x.shape[0]
        perp = min(self.perplexity, (n - 1) / 3.0)
        row_ptr, cols, vals = _sparse_sym_p(x, perp)
        rng = np.random.default_rng(self.seed)
        y = (1e-2 * rng.standard_normal((n, 2))).astype(np.float32)
        v = np.zeros_like(y)
        gains = np.ones_like(y)
        native = native_ops.available()
        exagg_iters = min(100, self.max_iter // 4)
        vals_ex = vals * 12.0     # early-exaggeration array, built ONCE
        for it in range(self.max_iter):
            v_it = vals_ex if it < exagg_iters else vals
            momentum = 0.5 if it < 250 else 0.8
            attr = (native_ops.bh_attraction(y, row_ptr, cols, v_it)
                    if native else None)
            if attr is None:
                attr = _np_attraction(y, row_ptr, cols, v_it)
            rz = native_ops.bh_repulsion(y, self.theta) if native else None
            if rz is None:
                rz = _np_repulsion(y)
            rep, Z = rz
            g = 4.0 * (attr - rep / Z)
            flips = np.sign(g) != np.sign(v)
            gains = np.clip(np.where(flips, gains + 0.2, gains * 0.8),
                            0.01, None)
            v = momentum * v - self.learning_rate * gains * g
            y = y + v
            y -= y.mean(0)
        self.embedding = np.asarray(y, np.float32)
        return self.embedding

    def plot(self, x, labels=None, path=None):
        """Fit and dump coordinates (+labels) to a TSV like the reference's
        saveCoordsForPlot."""
        coords = self.fit(x)
        if path:
            with open(path, "w", encoding="utf-8") as fh:
                for i, row in enumerate(coords):
                    lab = labels[i] if labels is not None else i
                    fh.write("\t".join(f"{v:.6f}" for v in row)
                             + f"\t{lab}\n")
        return coords


class BarnesHutTsne(Tsne):
    """reference: plot/BarnesHutTsne.java — always the O(N log N)
    theta-approximate path (quadtree repulsion + sparse kNN attraction),
    any N. Plain `Tsne` auto-selects between this and the exact dense
    TPU kernel by size."""

    def __init__(self, **kw):
        kw.setdefault("method", "barnes_hut")
        super().__init__(**kw)
