from .resilience import (FaultInjected, FaultInjector, NonRetryableError,
                         RetryPolicy)

__all__ = ["FaultInjected", "FaultInjector", "NonRetryableError",
           "RetryPolicy"]
