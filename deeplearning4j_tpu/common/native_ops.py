"""ctypes binding to the native runtime library (native/dl4j_tpu_native.cpp).

The reference's IO/data hot paths are native (SURVEY.md §2.9); this module
loads the C++ equivalents — IDX parsing, CSV parsing, staging-buffer pool —
and transparently builds the .so with `make` on first use if the toolchain is
available. Every caller has a pure-Python fallback, so a missing compiler
never breaks the framework (the reference's reflective-helper-with-fallback
pattern, ConvolutionLayer.java:69-76).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdl4j_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


_ABI_VERSION = 7  # must match dl4j_abi_version() in dl4j_tpu_native.cpp


def _try_build(force=False):
    global _build_attempted
    if _build_attempted:
        return
    _build_attempted = True
    try:
        cmd = ["make", "-C", _NATIVE_DIR] + (["-B"] if force else [])
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception as e:  # toolchain missing / build failure -> fallback
        log.debug("native build failed (%s); using python fallbacks", e)


def _load_checked():
    """CDLL + ABI version check; None if missing or mismatched."""
    if not os.path.exists(_SO_PATH):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        lib.dl4j_abi_version.restype = ctypes.c_int64
        if lib.dl4j_abi_version() != _ABI_VERSION:
            return None
    except (OSError, AttributeError):
        return None
    return lib


def get_lib():
    """Load (rebuilding if absent or ABI-stale) the native library, or
    None. A pre-existing .so built from older sources (the .so is not
    committed) fails the version check and triggers one forced rebuild
    rather than silently disabling the native paths."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = _load_checked()
        if lib is None:
            _try_build(force=os.path.exists(_SO_PATH))
            lib = _load_checked()
        if lib is None:
            return None
        lib.dl4j_read_idx_u8.restype = ctypes.POINTER(ctypes.c_float)
        lib.dl4j_read_idx_u8.argtypes = [
            ctypes.c_char_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_parse_csv.restype = ctypes.POINTER(ctypes.c_float)
        lib.dl4j_parse_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_free.argtypes = [ctypes.c_void_p]
        lib.dl4j_pool_create.restype = ctypes.c_void_p
        lib.dl4j_pool_create.argtypes = [ctypes.c_size_t]
        lib.dl4j_pool_acquire.restype = ctypes.c_void_p
        lib.dl4j_pool_acquire.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.dl4j_pool_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_size_t]
        lib.dl4j_pool_stats.restype = ctypes.c_int64
        lib.dl4j_pool_stats.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dl4j_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.dl4j_cbow_contexts.restype = ctypes.c_int64
        lib.dl4j_cbow_contexts.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.dl4j_glove_cooc.restype = ctypes.c_int64
        lib.dl4j_glove_cooc.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
        lib.dl4j_loader_create.restype = ctypes.c_void_p
        lib.dl4j_loader_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32]
        lib.dl4j_loader_next.restype = ctypes.POINTER(ctypes.c_float)
        lib.dl4j_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.dl4j_skipgram_pairs.restype = ctypes.c_int64
        lib.dl4j_skipgram_pairs.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.dl4j_bh_repulsion.restype = ctypes.c_double
        lib.dl4j_bh_repulsion.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
            ctypes.POINTER(ctypes.c_float)]
        lib.dl4j_bh_attraction.restype = None
        lib.dl4j_bh_attraction.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float)]
        _lib = lib
        return _lib


def available():
    return get_lib() is not None


# ---------------------------------------------------------------------------
# High-level wrappers (None on unavailability -> caller falls back)
# ---------------------------------------------------------------------------

def read_idx_u8(path, scale=1.0):
    """Parse a u8 IDX file -> float32 ndarray scaled by `scale`."""
    lib = get_lib()
    if lib is None:
        return None
    ndim = ctypes.c_int32()
    dims = (ctypes.c_int64 * 4)()
    count = ctypes.c_int64()
    ptr = lib.dl4j_read_idx_u8(str(path).encode(), float(scale),
                               ctypes.byref(ndim), dims, ctypes.byref(count))
    if not ptr:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    n = int(np.prod(shape))
    if n != count.value:  # C-side validated count must match; never read past it
        lib.dl4j_free(ptr)
        return None
    arr = np.ctypeslib.as_array(ptr, shape=(n,)).reshape(shape).copy()
    lib.dl4j_free(ptr)
    return arr


def parse_csv(path, delimiter=",", skip_lines=0):
    """Parse a numeric CSV -> float32 [rows, cols] ndarray."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    ptr = lib.dl4j_parse_csv(str(path).encode(),
                             ctypes.c_char(delimiter.encode()),
                             int(skip_lines), ctypes.byref(rows),
                             ctypes.byref(cols))
    if not ptr:
        return None
    n = rows.value * cols.value
    if n == 0:            # empty-but-valid file sentinel
        lib.dl4j_free(ptr)
        return np.zeros((0, 0), np.float32)
    arr = np.ctypeslib.as_array(ptr, shape=(n,)).reshape(
        rows.value, cols.value).copy()
    lib.dl4j_free(ptr)
    return arr


def skipgram_pairs(ids, offsets, window, seed):
    """Corpus-level word2vec reduced-window pair generation in C++
    (the host half of the reference's native AggregateSkipGram path).

    ids: int32 concatenated tokens; offsets: int64 [n_seq+1]; returns
    (centers, outs) int32 arrays, or None when the library is missing
    (caller uses the vectorized numpy path)."""
    lib = get_lib()
    if lib is None:
        return None
    ids = np.ascontiguousarray(ids, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    cap = int(ids.shape[0]) * 2 * int(window)
    centers = np.empty(cap, np.int32)
    outs = np.empty(cap, np.int32)
    n = lib.dl4j_skipgram_pairs(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        int(offsets.shape[0]) - 1, int(window), int(seed) & (2**64 - 1),
        centers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        outs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return centers[:n], outs[:n]


def cbow_contexts(ids, offsets, window, seed):
    """Corpus-level CBOW context-row generation in C++ (sibling of
    `skipgram_pairs` for the context->center objective). Returns
    (context [rows, 2*window] int32 with -1 padding, targets [rows]
    int32), or None when the library is missing."""
    lib = get_lib()
    if lib is None:
        return None
    ids = np.ascontiguousarray(ids, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    cap = int(ids.shape[0])
    context = np.empty((cap, 2 * int(window)), np.int32)
    targets = np.empty(cap, np.int32)
    n = lib.dl4j_cbow_contexts(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        int(offsets.shape[0]) - 1, int(window), int(seed) & (2**64 - 1),
        context.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        targets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return context[:n], targets[:n]


def pack_corpus(id_lists):
    """Concatenate per-sequence id lists into (ids int32, offsets int64)
    — the corpus layout every native generator consumes."""
    ids = np.concatenate([np.asarray(s, np.int32) for s in id_lists])
    offsets = np.zeros(len(id_lists) + 1, np.int64)
    np.cumsum([len(s) for s in id_lists], out=offsets[1:])
    return ids, offsets


def glove_cooc(ids, offsets, window, symmetric):
    """Windowed 1/distance co-occurrence counting in C++ (reference
    AbstractCoOccurrences role). Returns (i, j, x) COO arrays or None when
    the library is missing."""
    lib = get_lib()
    if lib is None:
        return None
    ids = np.ascontiguousarray(ids, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    pi = ctypes.POINTER(ctypes.c_int32)()
    pj = ctypes.POINTER(ctypes.c_int32)()
    px = ctypes.POINTER(ctypes.c_float)()
    n = lib.dl4j_glove_cooc(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        int(offsets.shape[0]) - 1, int(window), int(bool(symmetric)),
        ctypes.byref(pi), ctypes.byref(pj), ctypes.byref(px))
    if n < 0:
        return None
    if n == 0:
        for p in (pi, pj, px):
            lib.dl4j_free(p)
        z = np.zeros(0, np.int32)
        return z, z.copy(), np.zeros(0, np.float32)
    i = np.ctypeslib.as_array(pi, shape=(n,)).copy()
    j = np.ctypeslib.as_array(pj, shape=(n,)).copy()
    x = np.ctypeslib.as_array(px, shape=(n,)).copy()
    for p in (pi, pj, px):
        lib.dl4j_free(p)
    return i, j, x


def bh_repulsion(y, theta=0.5):
    """Barnes-Hut repulsive t-SNE forces (quadtree + theta traversal in
    C++, threaded). y: [n, 2] float32. Returns (rep [n, 2], Z) or None
    when the library is missing (caller falls back)."""
    lib = get_lib()
    if lib is None:
        return None
    y = np.ascontiguousarray(y, np.float32)
    rep = np.empty_like(y)
    z = lib.dl4j_bh_repulsion(
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(y.shape[0]), float(theta),
        rep.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return rep, float(z)


def bh_attraction(y, row_ptr, cols, vals):
    """Sparse attractive t-SNE forces from a CSR neighbor matrix in C++.
    Returns attr [n, 2] or None when the library is missing."""
    lib = get_lib()
    if lib is None:
        return None
    y = np.ascontiguousarray(y, np.float32)
    row_ptr = np.ascontiguousarray(row_ptr, np.int64)
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    attr = np.empty_like(y)
    lib.dl4j_bh_attraction(
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(y.shape[0]),
        row_ptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        attr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return attr


class PrefetchCsvLoader:
    """Multi-threaded native CSV prefetcher: worker threads parse files
    into float32 matrices off the GIL; `next()` yields them in submission
    order (the DataVec-reader + AsyncDataSetIterator host role, kept
    native per SURVEY.md §2.9). Context-manage or call close()."""

    def __init__(self, paths, delimiter=",", skip_lines=0, n_threads=4,
                 capacity=8):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        paths = [str(p) for p in paths]
        joined = "\n".join(paths).encode()
        self._handle = lib.dl4j_loader_create(
            joined, ctypes.c_char(delimiter.encode()), int(skip_lines),
            int(n_threads), int(capacity))
        if not self._handle:
            raise RuntimeError("loader creation failed")

    def next(self):
        """Next file's float32 [rows, cols] array; None when exhausted.
        Raises on a file that failed to parse."""
        if self._handle is None:
            return None
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        ptr = self._lib.dl4j_loader_next(self._handle, ctypes.byref(rows),
                                         ctypes.byref(cols))
        if not ptr:
            if rows.value == -1:
                return None
            raise IOError("native CSV parse failed for next file")
        n = rows.value * cols.value
        if n == 0:        # empty-but-valid file sentinel
            self._lib.dl4j_free(ptr)
            return np.zeros((0, 0), np.float32)
        arr = np.ctypeslib.as_array(ptr, shape=(n,)).reshape(
            rows.value, cols.value).copy()
        self._lib.dl4j_free(ptr)
        return arr

    def __iter__(self):
        while True:
            a = self.next()
            if a is None:
                return
            yield a

    def close(self):
        if self._handle is not None:
            self._lib.dl4j_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class StagingBufferPool:
    """Aligned reusable host buffers for device staging (reference role:
    ND4J AtomicAllocator host-side buffers / MagicQueue)."""

    def __init__(self, alignment=4096):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._pool = lib.dl4j_pool_create(alignment)

    def acquire(self, nbytes):
        ptr = self._lib.dl4j_pool_acquire(self._pool, int(nbytes))
        if not ptr:
            raise MemoryError(f"pool acquire({nbytes}) failed")
        return ptr

    def release(self, ptr, nbytes):
        self._lib.dl4j_pool_release(self._pool, ptr, int(nbytes))

    def as_array(self, ptr, shape, dtype=np.float32):
        n = int(np.prod(shape))
        ctype = np.ctypeslib.as_ctypes_type(np.dtype(dtype))
        buf = ctypes.cast(ptr, ctypes.POINTER(ctype * n)).contents
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def stats(self):
        return {"allocated": self._lib.dl4j_pool_stats(self._pool, 0),
                "reused": self._lib.dl4j_pool_stats(self._pool, 1),
                "free": self._lib.dl4j_pool_stats(self._pool, 2)}

    def close(self):
        if self._pool:
            self._lib.dl4j_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
