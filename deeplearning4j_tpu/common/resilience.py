"""Fault-tolerance primitives: retry policies and deterministic fault
injection.

The reference stack leans on Aeron (lossy-network-tolerant UDP transport)
and Spark (task re-execution) for resilience; the TCP reimplementation here
(`parallel/ps_transport.py`, `parallel/training_master.py`) needs those
semantics made explicit. This module is the one place they are defined:

  * `RetryPolicy` — bounded exponential backoff with deterministic jitter,
    an optional wall-clock deadline over all attempts, and retryable-
    exception classification. Shared by the PS client's reconnect path and
    available to any caller that talks across a process/network boundary.
  * `NonRetryableError` — marker mix-in: an exception carrying it is never
    retried, regardless of the policy's `retryable` tuple (e.g. a push the
    server REFUSED is a terminal condition, while a dropped connection is
    not, even though both subclass ConnectionError).
  * `RetryBudget` — fleet-wide token-bucket retry budget (Google-SRE
    style: retries refill as a fraction of successes), shared between
    every retrying path via `RetryPolicy(budget=...)`; exhaustion turns
    a retry into a loud `RetryBudgetExhaustedError` instead of load
    amplification.
  * `FaultInjector` — deterministic, seeded fault schedules keyed by call
    site. Production code exposes named sites (`client.push.sent`,
    `master.round`, `data.batch`, ...) and the injector decides per call
    whether to delay, sever a connection, corrupt a payload (NaN/Inf/
    value-poison — the data-path fault the training-health watchdog
    handles), and/or raise — so every failure mode the retry/heartbeat/
    resume/watchdog machinery handles has a repeatable test driving it
    through the REAL code path, not a mock.

Everything here is stdlib-only (no jax/numpy): the PS worker side is
numpy-only by design and must stay importable without jax. Retries and
injected faults additionally publish to the process-wide
`obs.registry.default_registry()` (also stdlib-only) — `resilience.
retries[.<metric>]` / `resilience.faults_injected[.<site>]` — so the
`/metrics` Prometheus route on ui/server.py shows transport health next
to serving and training counters.
"""
from __future__ import annotations

import logging
import random
import threading
import time

from ..obs.registry import default_registry

log = logging.getLogger(__name__)


class NonRetryableError(Exception):
    """Marker mix-in: never retried by any RetryPolicy, even when the
    concrete type also matches the policy's `retryable` classes."""


class RetryBudgetExhaustedError(NonRetryableError, RuntimeError):
    """The shared fleet-wide retry budget denied this retry: the
    failure is delivered LOUDLY instead of amplified into another
    replay/resend. Carries the NonRetryableError marker so no nested
    RetryPolicy ever retries the refusal itself."""


class FaultInjected(ConnectionError):
    """Default exception raised at an injected fault site."""


class RetryBudget:
    """Fleet-wide token-bucket retry budget (the Google-SRE "retry
    budget": retries are paid for by SUCCESSES, so past the saturation
    knee the recovery machinery cannot amplify offered load — the
    metastable-failure regime).

    One instance is SHARED by every path that retries on the fleet's
    behalf: the manager's failover replays (serving/fleet.py) and the
    wire transport's reconnect/resend loops (serving/wire.py, via
    `RetryPolicy.budget`). `take()` spends one token per retry and
    returns False when the bucket is dry — the caller converts the
    denial into a loud typed failure (`RetryBudgetExhaustedError`),
    never a silent drop. `on_success()` refills `refill_fraction`
    tokens per successful completion, capped at `capacity`, so a
    healthy fleet always has budget and a melting one starves its own
    retry storm. A fleet that never retries never touches the bucket —
    the no-fault A/B is byte-identical with or without a budget."""

    def __init__(self, capacity=64, refill_fraction=0.1, initial=None):
        self.capacity = float(capacity)
        self.refill_fraction = float(refill_fraction)
        if self.capacity < 0 or self.refill_fraction < 0:
            raise ValueError("need capacity >= 0 and "
                             "refill_fraction >= 0")
        self._tokens = (self.capacity if initial is None
                        else min(float(initial), self.capacity))
        self._lock = threading.Lock()
        self.denied = 0         # lifetime denial count (observability)

    @property
    def tokens(self):
        with self._lock:
            return self._tokens

    def take(self, n=1):
        """Spend `n` tokens for a retry; False = budget exhausted (the
        caller must fail loudly, not wait)."""
        with self._lock:
            if self._tokens >= n:
                self._tokens -= n
                return True
            self.denied += 1
            return False

    def on_success(self, n=1):
        """Refill from `n` successful completions."""
        with self._lock:
            self._tokens = min(self.capacity,
                               self._tokens + self.refill_fraction * n)


class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    delay(attempt) = min(max_delay, base_delay * multiplier**attempt),
    scaled by a seeded uniform jitter in [1-jitter, 1+jitter] — the seed
    makes backoff sequences reproducible in tests while still decorrelating
    real workers (give each worker a different seed).

    `deadline` bounds the TOTAL wall clock across all attempts: the final
    backoff sleep is CAPPED by the remaining deadline (never sleeping past
    it), buying one last attempt at the deadline edge; once the deadline
    is spent the last error re-raises. `sleep`/`clock` are injectable for
    tests (fake time).
    """

    def __init__(self, max_retries=5, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.25, deadline=None,
                 retryable=(ConnectionError, TimeoutError, OSError),
                 seed=0, sleep=None, clock=None, metric=None,
                 budget=None):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        # `metric`: optional name suffix for the registry counter, so a
        # PS client's reconnect retries and a serving dispatch's retries
        # are distinguishable on the /metrics route
        self.metric = metric
        # `budget`: optional shared RetryBudget — the fleet-wide retry
        # gate every holder of this policy consults via grant_retry()
        # before spending an attempt. None (default) = unbudgeted, the
        # exact legacy behavior.
        self.budget = budget
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)
        self.retryable = tuple(retryable)
        self._rng = random.Random(seed)
        self._sleep = sleep if sleep is not None else time.sleep
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()

    def is_retryable(self, exc):
        if isinstance(exc, NonRetryableError):
            return False
        return isinstance(exc, self.retryable)

    def grant_retry(self, n=1):
        """Consult the shared retry budget (True when unbudgeted). One
        call per retry ATTEMPT, made at the spend site — the policy
        itself stays stateless across holders."""
        return self.budget is None or self.budget.take(n)

    def delay(self, attempt):
        """Backoff before retry number `attempt` (0-based). Consumes one
        jitter draw from the seeded rng (thread-safe)."""
        d = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        if self.jitter:
            with self._lock:
                u = self._rng.uniform(-1.0, 1.0)
            d = max(0.0, d * (1.0 + self.jitter * u))
        return d

    def call(self, fn, on_retry=None):
        """Run `fn()` with retries. `on_retry(attempt, exc, delay)` fires
        before each backoff sleep (logging/metrics hook). Non-retryable
        exceptions, exhausted attempts, and deadline overruns re-raise the
        last error unchanged."""
        start = self._clock()
        attempt = 0
        while True:
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self.is_retryable(e) or attempt >= self.max_retries:
                    raise
                if not self.grant_retry():
                    raise RetryBudgetExhaustedError(
                        f"retry budget exhausted after "
                        f"{type(e).__name__}: {e}") from e
                d = self.delay(attempt)
                if self.deadline is not None:
                    remaining = self.deadline - (self._clock() - start)
                    if remaining <= 0:
                        raise
                    # cap the final backoff by the remaining wall clock:
                    # never sleep past the deadline, but do spend the
                    # remainder on one last attempt instead of forfeiting
                    # it by raising early
                    d = min(d, remaining)
                reg = default_registry()
                reg.counter("resilience.retries").inc()
                if self.metric:
                    reg.counter(
                        f"resilience.retries.{self.metric}").inc()
                if on_retry is not None:
                    on_retry(attempt, e, d)
                self._sleep(d)
                attempt += 1


class _Rule:
    __slots__ = ("on_calls", "prob", "remaining", "exc", "delay", "sever",
                 "corrupt")


def _poison(payload, value):
    """Duck-typed payload corruption: fill a COPY of an array-like payload
    with `value` (float('nan'), float('inf'), or any finite float —
    finite poison models the loss-spike class, non-finite the NaN/Inf
    class). The original is never mutated — sites rebind the returned
    payload, matching the pipeline's rebind-only contract. Covers numpy
    (.copy + in-place .fill), immutable array types like jax.Array
    (arithmetic broadcast keeps shape/dtype), and bare scalars."""
    copy = getattr(payload, "copy", None)
    fill = getattr(payload, "fill", None)
    if callable(copy) and callable(fill):
        out = payload.copy()
        out.fill(value)
        return out
    if hasattr(payload, "shape") and hasattr(payload, "__mul__"):
        # immutable arrays (jax.Array): broadcast the poison, same shape
        return payload * 0 + value
    return value


class FaultInjector:
    """Deterministic fault schedules keyed by instrumented call site.

    A site is a string name a production code path fires on every pass
    (`injector.fire("client.push.sent", on_sever=...)`); each site keeps a
    call counter. Rules planned against the site decide, per call, whether
    to inject — by explicit call index (`on_call`/`on_calls`, exactly
    reproducible) or by seeded probability (`prob`, reproducible for a
    given seed + call sequence). A firing rule can sleep (`delay`), invoke
    the site's sever callback (`sever=True` — e.g. the PS client closes its
    socket, simulating a network cut), corrupt the payload the site passed
    (`corrupt`: "nan" / "inf" / a float — `fire` returns a poisoned COPY
    the site rebinds, the data-path analog of a network fault), and raise
    (`exc`: class or instance; None = fault without raising, for pure
    delay/sever/corrupt).

    `times` caps how often a rule fires (default: once per planned call
    index, or once for prob/always rules).
    """

    def __init__(self, seed=0):
        self._rng = random.Random(seed)
        self._rules = {}
        self._calls = {}
        self._fired = []
        self._lock = threading.Lock()
        self._sleep = time.sleep

    def plan(self, site, on_call=None, on_calls=None, prob=None, times=None,
             exc=FaultInjected, delay=0.0, sever=False, corrupt=None):
        """Schedule a fault at `site`; returns self for chaining.

        `corrupt`: poison the site's payload — "nan", "inf", or any float
        fill value. A corrupt-only plan defaults `exc` to None (the
        poisoned payload flowing onward IS the fault; raising as well
        would mask the data path under test). Pass `exc` explicitly to
        combine."""
        if on_call is not None and on_calls is not None:
            raise ValueError("pass on_call or on_calls, not both")
        if on_call is not None:
            on_calls = [on_call]
        rule = _Rule()
        rule.on_calls = (None if on_calls is None
                         else {int(c) for c in on_calls})
        rule.prob = None if prob is None else float(prob)
        if times is None:
            times = len(rule.on_calls) if rule.on_calls is not None else 1
        rule.remaining = int(times)
        if corrupt is None:
            rule.corrupt = None
        else:
            named = {"nan": float("nan"), "inf": float("inf")}
            rule.corrupt = (named[corrupt] if isinstance(corrupt, str)
                            else float(corrupt))
            if exc is FaultInjected:
                exc = None
        rule.exc = exc
        rule.delay = float(delay)
        rule.sever = bool(sever)
        with self._lock:
            self._rules.setdefault(site, []).append(rule)
        return self

    def fire(self, site, on_sever=None, payload=None):
        """Instrumentation point: bump the site's call counter and apply
        the first matching rule (delay -> sever -> corrupt -> raise).
        Returns `payload` — poisoned (a corrupted COPY; the site must
        rebind it) when a corrupt rule fired, untouched otherwise."""
        with self._lock:
            n = self._calls.get(site, 0)
            self._calls[site] = n + 1
            hit = None
            for rule in self._rules.get(site, ()):
                if rule.remaining <= 0:
                    continue
                if rule.on_calls is not None:
                    match = n in rule.on_calls
                elif rule.prob is not None:
                    match = self._rng.random() < rule.prob
                else:
                    match = True
                if match:
                    rule.remaining -= 1
                    hit = rule
                    self._fired.append((site, n))
                    break
        if hit is None:
            return payload
        reg = default_registry()
        reg.counter("resilience.faults_injected").inc()
        reg.counter(f"resilience.faults_injected.{site}").inc()
        log.warning("fault injected at %s (call #%d): delay=%.3fs sever=%s"
                    " corrupt=%s", site, n, hit.delay, hit.sever,
                    hit.corrupt)
        if hit.delay:
            self._sleep(hit.delay)
        if hit.sever and on_sever is not None:
            on_sever()
        if hit.corrupt is not None and payload is not None:
            payload = _poison(payload, hit.corrupt)
        exc = hit.exc
        if exc is None:
            return payload
        if isinstance(exc, BaseException):
            raise exc
        raise exc(f"injected fault at {site} (call #{n})")

    def calls(self, site):
        """How many times `site` has fired its instrumentation point."""
        with self._lock:
            return self._calls.get(site, 0)

    def fired(self, site=None):
        """(site, call_index) events for faults actually injected."""
        with self._lock:
            return [e for e in self._fired if site is None or e[0] == site]
