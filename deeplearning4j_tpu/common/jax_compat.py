"""jax version-compatibility shims.

The codebase targets current jax (top-level `jax.shard_map`, `check_vma`
kwarg); older jaxlib stacks (0.4.x) ship shard_map under
`jax.experimental.shard_map` with the replication check named `check_rep`.
This module is the ONE place that difference lives — import `shard_map`
from here, pass either kwarg name, and the active jax gets the one it
understands. Kept out of `common/__init__` so the numpy-only worker paths
(`common.resilience`, PS clients) never pull jax in transitively.
"""
from __future__ import annotations

try:                          # jax >= 0.6: top-level export, check_vma
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:           # older jax: experimental namespace, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, **kwargs):
    for alias in ("check_vma", "check_rep"):
        if alias in kwargs and alias != _CHECK_KW:
            kwargs[_CHECK_KW] = kwargs.pop(alias)
    return _shard_map(f, **kwargs)
