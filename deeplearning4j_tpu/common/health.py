"""Training-health watchdog: numerical-fault classification and response.

PR 1 (`common/resilience.py`) made the stack survive *infrastructure*
faults; this module makes it survive *numerical* ones — the failure class
production training logs are full of (PaLM's skip-and-rollback on loss
spikes, Chowdhery et al. 2022; the OPT-175B logbook's manual
restart-below-the-spike loop, Zhang et al. 2022). Three pieces:

  * Device side (`grad_health`, `gate_update`): the fused train step
    optionally emits a scalar health pytree — global/per-layer gradient
    norms, the score, and an all-finite flag — and applies the parameter/
    updater/model-state update *conditionally* (`jnp.where` on the
    all-finite predicate), so a poisoned batch is skipped inside one
    compiled program with no host round-trip. With the watchdog disarmed
    the step compiles the identical HLO as before (same contract as the
    activation-stats emission; pinned by test).

  * Host side (`TrainingHealthPolicy`): stateful classification of each
    step's health dict — NaN/Inf (the device already skipped), EMA-z-score
    loss spike, gradient-norm explosion — into an action: count-and-skip,
    rollback-to-last-good-round, or abort-after-N-consecutive with a loud
    diagnostic naming the offending rounds. stdlib only; the health values
    it reads may be jnp scalars (one `float()` sync per step).

  * Loop driver (`apply_policy`, `install`): the one action-dispatch shared
    by every training loop (MultiLayerNetwork/ComputationGraph `fit`,
    ParallelWrapper allreduce and k-local-steps modes, TrainingMaster).
    Rollback goes through the PR 1 round-checkpoint seam — a
    `ShardedCheckpointManager` restore of the newest round, which also
    rewinds rng and counters so the post-rollback stream replays exactly
    (the crash-resume bit-comparability bar).

Watchdog events (skips, spikes, rollbacks, validation rejects) are kept in
the policy's bounded event log; `ui/stats.py` StatsListener reads
`snapshot()` into each report so run health reaches the UI storage.
"""
from __future__ import annotations

import collections
import logging
import math
import threading

log = logging.getLogger(__name__)

# actions returned by TrainingHealthPolicy.observe / apply_policy
OK = "ok"            # healthy step
SKIP = "skip"        # non-finite: the device already skipped the update
SPIKE = "spike"      # divergence counted but not undone (no rollback seam)
ROLLBACK = "rollback"  # divergence: restore the last good round
ABORT = "abort"      # N consecutive unhealthy steps: stop the run


class TrainingDivergedError(RuntimeError):
    """Raised when the watchdog aborts a run after `max_consecutive_bad`
    consecutive unhealthy steps. The message names the offending rounds."""


# ---------------------------------------------------------------------------
# Device side — used INSIDE the fused (jitted) train step
# ---------------------------------------------------------------------------

def grad_health(grads, score):
    """Scalar health pytree of one step, computed on device.

    `grads` is the container's gradient pytree (list-of-dicts for
    MultiLayerNetwork, name-keyed dict-of-dicts for ComputationGraph).
    Returns {"score", "grad_norm", "layer_grad_norms", "all_finite"} —
    a few f32/bool scalars per layer, negligible device->host traffic.

    Finiteness is read off the squared-norm accumulation: squares are
    non-negative (no cancellation), so the total is non-finite iff some
    gradient element is NaN/Inf — or the norm itself overflowed f32,
    which is a gradient explosion and equally skip-worthy.
    """
    import jax.numpy as jnp
    if isinstance(grads, dict):
        items = list(grads.items())
    else:
        items = [(str(i), g) for i, g in enumerate(grads)]
    layer_norms = {}
    total_sq = jnp.asarray(0.0, jnp.float32)
    for name, group in items:
        sq = jnp.asarray(0.0, jnp.float32)
        for leaf in _leaves(group):
            sq = sq + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        layer_norms[name] = jnp.sqrt(sq)
        total_sq = total_sq + sq
    score32 = jnp.asarray(score, jnp.float32)
    return {
        "score": score32,
        "grad_norm": jnp.sqrt(total_sq),
        "layer_grad_norms": layer_norms,
        "all_finite": jnp.isfinite(total_sq) & jnp.isfinite(score32),
    }


def _leaves(tree):
    import jax
    return jax.tree.leaves(tree)


def rowwise_finite(tree, batch_axis=0):
    """Per-example finiteness of an inference OUTPUT pytree: bool [B]
    numpy vector, True where every leaf's row `b` is all-finite. The
    serving layer's optional output screen (`InferenceServer(
    screen_outputs=True)`) uses it to fail ONLY the poisoned requests in
    a micro-batch instead of the whole dispatch — the inference-side
    analog of the training watchdog's NaN/Inf skip. Host-side numpy on
    results that are already being shipped to callers, so it adds no
    device round-trip."""
    import numpy as np
    ok = None
    for leaf in _leaves(tree):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
            continue                  # ints/bools can't be non-finite
        if a.dtype.kind not in "fc":
            # ml_dtypes bfloat16/f8 (kind 'V'): no native isfinite — the
            # f32 cast is exact for them. Native f16/f32/f64/complex are
            # checked in their OWN precision (casting f64 to f32 would
            # flag finite values beyond f32 range as inf).
            a = a.astype(np.float32)
        axes = tuple(i for i in range(a.ndim) if i != batch_axis)
        row_ok = np.isfinite(a).all(axis=axes)
        ok = row_ok if ok is None else (ok & row_ok)
    return ok


def gate_update(ok, new_tree, old_tree):
    """Conditionally apply an update inside the compiled step: every leaf
    becomes `jnp.where(ok, new, old)`, so a step whose health predicate is
    False leaves params/updater-state/model-state bit-identical — no host
    round-trip, no recompile, no branch."""
    import jax
    import jax.numpy as jnp
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                        new_tree, old_tree)


# ---------------------------------------------------------------------------
# Host side — classification policy
# ---------------------------------------------------------------------------

class TrainingHealthPolicy:
    """Classify per-step health and decide the response.

    Classification (in precedence order):
      1. non-finite score/gradients -> SKIP (the device already withheld
         the update; the host counts it and moves on);
      2. gradient-norm explosion (`grad_norm_limit`) or loss spike (score
         more than `spike_zscore` EW-standard-deviations above the
         exponential moving average of *healthy* scores, after
         `warmup_steps` healthy observations) -> ROLLBACK (or SPIKE when
         the caller has no rollback seam / `rollback_on_spike=False`);
      3. `max_consecutive_bad` consecutive unhealthy steps -> ABORT
         (raised as TrainingDivergedError by `apply_policy`).

    The EMA baseline only ingests healthy steps, so a spike cannot poison
    its own detector. Counters and a bounded event log feed the UI
    (`snapshot()`); `record_validation_reject` lets the data-pipeline
    validator aggregate into the same run-health view.
    """

    def __init__(self, spike_zscore=6.0, ema_decay=0.9, warmup_steps=8,
                 grad_norm_limit=None, max_consecutive_bad=5,
                 rollback_on_spike=True, max_events=64):
        if max_consecutive_bad < 1:
            raise ValueError("max_consecutive_bad must be >= 1")
        self.spike_zscore = float(spike_zscore)
        self.ema_decay = float(ema_decay)
        self.warmup_steps = int(warmup_steps)
        self.grad_norm_limit = (None if grad_norm_limit is None
                                else float(grad_norm_limit))
        self.max_consecutive_bad = int(max_consecutive_bad)
        self.rollback_on_spike = bool(rollback_on_spike)
        self.counts = {"ok": 0, "skips": 0, "spikes": 0, "rollbacks": 0,
                       "aborts": 0, "validation_rejects": 0}
        self.events = collections.deque(maxlen=int(max_events))
        self.consecutive_bad = 0
        self._ema = None
        self._var = 0.0
        self._healthy_seen = 0
        # observe() runs on the training thread, but validation rejects
        # arrive from the async staging pool's threads — guard the shared
        # counters/events so concurrent rejects don't lose increments
        self._lock = threading.Lock()

    def _count(self, key, n=1):
        """Increment a health counter AND mirror it onto the process-wide
        metrics registry (`train.health.<key>`), so run-health shows up
        on the ui/server.py `/metrics` Prometheus route next to serving
        and transport counters — the one named surface."""
        self.counts[key] += n
        from ..obs.registry import default_registry
        default_registry().counter("train.health." + key).inc(n)

    # -- classification -------------------------------------------------
    def observe(self, health, round_index=None):
        """Classify one step. Returns OK / SKIP / SPIKE / ROLLBACK /
        ABORT. `health` is the step's emitted dict (jnp or python
        scalars)."""
        score = float(health["score"])
        grad_norm = float(health["grad_norm"])
        finite = bool(health["all_finite"])
        if not finite:
            bad = int(health.get("bad_steps", 1))
            steps = int(health.get("steps", 1))
            if 0 < bad < steps:
                # k-local-steps partial round: only some of the round's
                # local device-steps were non-finite, and each was already
                # skipped on ITS device — the averaged round still
                # progressed and its score covers the healthy steps.
                # Count the skips; don't escalate, don't starve the
                # checkpoint cadence. (The round's pmax grad-norm is
                # contaminated by the skipped step, so spike checks are
                # meaningless here and deliberately not applied.)
                self._count("skips", bad)
                self.consecutive_bad = 0
                self._event("skip", round_index,
                            reason=f"{bad}/{steps} local steps non-finite "
                                   "(partial round, average applied)",
                            score=score, gradNorm=grad_norm)
                log.warning("training-health partial skip at round %s: "
                            "%d/%d local steps non-finite", round_index,
                            bad, steps)
                return OK
            return self._unhealthy(SKIP, "non-finite score/gradients",
                                   round_index, score, grad_norm)
        reason = None
        if (self.grad_norm_limit is not None
                and grad_norm > self.grad_norm_limit):
            reason = (f"gradient norm {grad_norm:.4g} exceeds limit "
                      f"{self.grad_norm_limit:.4g}")
        else:
            z = self._zscore(score)
            if z is not None and z > self.spike_zscore:
                reason = (f"loss spike: score {score:.4g} is {z:.1f} "
                          f"EW-stdev above EMA {self._ema:.4g}")
        if reason is not None:
            want = ROLLBACK if self.rollback_on_spike else SPIKE
            return self._unhealthy(want, reason, round_index, score,
                                   grad_norm)
        self._count("ok")
        self.consecutive_bad = 0
        self._ingest(score)
        return OK

    def _zscore(self, score):
        if self._ema is None or self._healthy_seen < self.warmup_steps:
            return None
        std = math.sqrt(max(self._var, 0.0))
        scale = max(std, abs(self._ema) * 1e-3, 1e-12)
        return (score - self._ema) / scale

    def _ingest(self, score):
        self._healthy_seen += 1
        if self._ema is None:
            self._ema = score
            return
        d = self.ema_decay
        delta = score - self._ema
        self._ema += (1.0 - d) * delta
        self._var = d * (self._var + (1.0 - d) * delta * delta)

    def _unhealthy(self, want, reason, round_index, score, grad_norm):
        kind = "skip" if want == SKIP else "spike"
        self._count(kind + "s")
        self.consecutive_bad += 1
        self._event(kind, round_index, reason=reason, score=score,
                    gradNorm=grad_norm)
        log.warning("training-health %s at round %s: %s", kind,
                    round_index, reason)
        if self.consecutive_bad >= self.max_consecutive_bad:
            self._count("aborts")
            self._event("abort", round_index, reason=reason)
            return ABORT
        return want

    # -- bookkeeping hooks ----------------------------------------------
    def record_rollback(self, round_index, restored_round):
        self._count("rollbacks")
        self._event("rollback", round_index,
                    restoredRound=int(restored_round))
        log.warning("training-health rollback: round %s restored from "
                    "checkpointed round %s", round_index, restored_round)

    def record_validation_reject(self, reason, batch_index=None):
        with self._lock:
            self._count("validation_rejects")
        self._event("validation_reject", batch_index, reason=str(reason))

    def _event(self, kind, round_index, **meta):
        e = {"kind": kind,
             "round": None if round_index is None else int(round_index)}
        e.update(meta)
        with self._lock:
            self.events.append(e)

    # -- reporting ------------------------------------------------------
    def snapshot(self):
        """JSON-able run-health summary for the StatsListener report."""
        with self._lock:
            return {"counts": dict(self.counts),
                    "consecutiveBad": int(self.consecutive_bad),
                    "lastEvent": self.events[-1] if self.events else None}

    def diagnose(self):
        """Loud abort diagnostic naming the offending rounds."""
        with self._lock:       # a staging thread may be appending events
            events = list(self.events)
        bad = [e for e in events if e["kind"] in ("skip", "spike")]
        rounds = [e["round"] for e in bad[-self.consecutive_bad:]]
        last = bad[-1] if bad else {}
        return (f"training diverged: {self.consecutive_bad} consecutive "
                f"unhealthy steps (limit {self.max_consecutive_bad}); "
                f"offending rounds {rounds}; last: round {last.get('round')}"
                f" ({last.get('reason', 'unknown')})")


# ---------------------------------------------------------------------------
# Loop driver — shared by every training loop
# ---------------------------------------------------------------------------

def apply_policy(policy, health, round_index, rollback=None):
    """Classify one step and drive the host-side action. Returns the
    action actually taken (OK / SKIP / SPIKE / ROLLBACK); raises
    TrainingDivergedError on ABORT.

    `rollback` is the loop's seam to the last good round: a zero-arg
    callable returning the restored round number, or False/None when no
    checkpoint exists (the action then degrades to SPIKE: counted, params
    left as-is, escalating to abort if divergence persists).
    """
    action = policy.observe(health, round_index)
    if action == ABORT:
        raise TrainingDivergedError(policy.diagnose())
    if action == ROLLBACK:
        restored = rollback() if rollback is not None else None
        if restored is None or restored is False:
            log.warning("training-health: divergence at round %s but no "
                        "checkpoint to roll back to; counting and "
                        "continuing", round_index)
            return SPIKE
        policy.record_rollback(round_index, restored)
        return ROLLBACK
    return action


def install(net, policy=True, checkpoint_dir=None, checkpoint_every=10,
            keep_checkpoints=3):
    """Arm (or disarm) the training-health watchdog on a network — the one
    implementation behind MultiLayerNetwork.training_health and
    ComputationGraph.training_health.

    policy: a TrainingHealthPolicy, True for the defaults, or None/False
    to disarm. checkpoint_dir (optional) gives the single-process fit
    loops their rollback seam: a ShardedCheckpointManager under it saves
    the full training state every `checkpoint_every` healthy iterations,
    and a divergence restores the newest save (params, updater state, rng
    AND counters — the post-rollback step stream replays exactly).
    Without it, divergence degrades to count-and-continue; ParallelWrapper
    and TrainingMaster supply their own round-checkpoint seam instead.

    Arming/disarming costs one recompile (the step's return pytree gains/
    loses the health scalars); the disarmed step compiles the identical
    HLO as a never-armed one.
    """
    if policy is True:
        policy = TrainingHealthPolicy()
    elif policy is False:
        policy = None
    armed = policy is not None
    net._health_policy = policy
    net._health_gen = getattr(net, "_health_gen", 0) + 1
    net._jit_step = None                 # recompile with/without health
    net._health_ckpt = None
    net._health_ckpt_every = max(1, int(checkpoint_every))
    if armed and checkpoint_dir is not None:
        from ..util.sharded_checkpoint import ShardedCheckpointManager
        net._health_ckpt = ShardedCheckpointManager(
            str(checkpoint_dir), keep_last=max(1, int(keep_checkpoints)))
    return net


def finish_step(net, health, score):
    """The armed fit-loop step epilogue shared by MultiLayerNetwork and
    ComputationGraph (batch AND TBPTT loops): classify the emitted
    health, drive the host action through the net's checkpoint seam, and
    gate the score update (a skipped step's NaN must not become
    net._score). Returns the action — "rollback" means counters/rng were
    already restored and the caller must abandon the current
    batch/sequence; ABORT raises TrainingDivergedError."""
    rollback = None
    if getattr(net, "_health_ckpt", None) is not None:
        def rollback():
            return fit_loop_rollback(net)
    action = apply_policy(net._health_policy, health,
                          round_index=net.conf.iteration_count,
                          rollback=rollback)
    if action not in (ROLLBACK, SKIP):
        net._score = score
    return action


def split_stacked(health, n_steps):
    """Per-step classification over a stacked report: a fused K-step
    dispatch emits its health scalars as scan ys (leading axis K); this
    materializes the WHOLE report with one device->host sync and splits
    it into K per-step dicts for `observe`/`finish_step` — the host-side
    cost per dispatch is one transfer, not K scalar readbacks."""
    import jax
    import numpy as np
    host = jax.tree.map(np.asarray, health)
    return [jax.tree.map(lambda a: a[i], host) for i in range(n_steps)]


def finish_fused(net, scores, health_stack, n_steps):
    """The fused-dispatch epilogue shared by MultiLayerNetwork and
    ComputationGraph (super-batch AND TBPTT fused paths): walk the K
    inner steps of one dispatch in order, updating the score, counters
    and listeners per OPTIMIZER STEP (StatsListener sees every step, not
    every dispatch) and — when armed — classifying each step's health
    exactly as the sequential loop would.

    Returns the inner index whose classification triggered a ROLLBACK
    (counters/rng already restored; the caller re-runs the REMAINING
    staged batches from the restored state so the stream matches K
    sequential dispatches), or None when every step was consumed. ABORT
    raises TrainingDivergedError, as in the sequential loop."""
    import numpy as np
    if health_stack is None and not net.listeners:
        # nothing consumes per-step scalars: DON'T materialize the
        # stacked scores — the np.asarray would block the training
        # thread on the whole dispatch, serializing host group-staging
        # with device compute (the sequential loop never syncs). The
        # score is the super-batch's last step's, read lazily.
        net._score = scores[n_steps - 1]
        net.conf.iteration_count += n_steps
        return None
    scores_np = np.asarray(scores)
    healths = (split_stacked(health_stack, n_steps)
               if health_stack is not None else None)
    action = OK
    for i in range(n_steps):
        if healths is None:
            net._score = scores_np[i]
            action = OK
        else:
            action = finish_step(net, healths[i], scores_np[i])
            if action == ROLLBACK:
                return i
        net.conf.iteration_count += 1
        for l in net.listeners:
            l.iteration_done(net, net.conf.iteration_count - 1)
    # groups are clipped at checkpoint boundaries (fused.group_size), so
    # a due save can only land on the LAST inner step — where the net's
    # in-memory state IS the post-due-step state
    if healths is not None and action == OK:
        fit_loop_checkpoint(net)
    return None


def fit_loop_rollback(net):
    """Single-process fit loops' rollback seam: restore the newest health
    checkpoint INTO the net (counters, rng and device loop state
    included). Returns the restored round (iteration) number, or False
    when no checkpoint exists yet."""
    mgr = getattr(net, "_health_ckpt", None)
    if mgr is None or mgr.latest_step() is None:
        return False
    last = mgr.latest_step()
    mgr.restore(net, last)
    return last


def fit_loop_checkpoint(net):
    """Periodic save for the fit-loop seam: checkpoint the full training
    state at the current iteration count when due."""
    mgr = getattr(net, "_health_ckpt", None)
    if mgr is None:
        return
    it = int(net.conf.iteration_count)
    if it % net._health_ckpt_every == 0:
        score = getattr(net, "_score", None)
        score = None if score is None else float(score)
        if score is not None and not math.isfinite(score):
            score = None       # a NaN score must not enter best-step math
        mgr.save(net, it, score=score)
