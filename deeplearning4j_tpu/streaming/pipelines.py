"""Streaming inference / training pipelines.

TPU-native equivalent of reference dl4j-streaming pipeline/
(SparkStreamingPipeline.java — train from a Kafka topic — and
SparkStreamingInferencePipeline.java — Kafka features in, predictions out,
wired through Camel routes). Here a pipeline owns a Broker subscription and
a worker thread; batching happens host-side and every consumed batch goes
through the same jitted fit/output paths as offline training.
"""
from __future__ import annotations

import threading

from . import serde


class StreamingInferencePipeline:
    """Consume feature arrays from `input_topic`, publish predictions to
    `output_topic`. reference: SparkStreamingInferencePipeline.java."""

    def __init__(self, model, broker, input_topic="features",
                 output_topic="predictions"):
        self.model = model
        self.broker = broker
        self.input_topic = input_topic
        self.output_topic = output_topic
        self._sub = None
        self._thread = None
        self._stop = threading.Event()
        self.processed = 0
        self._error = None

    def start(self):
        self._sub = self.broker.subscribe(self.input_topic)
        self._stop.clear()
        self._error = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        try:
            while not self._stop.is_set():
                payload = self._sub.get(timeout=0.1)
                if payload is None:
                    continue
                x = serde.decode_array(payload)
                out = self.model.output(x)
                if isinstance(out, (list, tuple)):   # CG outputs
                    out = out[0]
                self.broker.publish(self.output_topic,
                                    serde.encode_array(out))
                self.processed += 1
        except Exception as e:   # surfaced by error()/stop(), not swallowed
            self._error = e

    def error(self):
        """Worker-thread failure, if any (a bad payload or model error
        stops consumption; callers can poll this between publishes)."""
        return self._error

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._error is not None:
            raise self._error


class StreamingTrainingPipeline:
    """Consume serialized DataSets from `input_topic` and fit the model on
    each (mini-batch online training). reference:
    SparkStreamingPipeline.java (kafka -> RDD -> fit per micro-batch)."""

    def __init__(self, model, broker, input_topic="train",
                 score_topic=None):
        self.model = model
        self.broker = broker
        self.input_topic = input_topic
        self.score_topic = score_topic
        self._sub = None
        self._thread = None
        self._stop = threading.Event()
        self.batches_fit = 0
        self._error = None

    def start(self):
        self._sub = self.broker.subscribe(self.input_topic)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        import numpy as np
        try:
            while not self._stop.is_set():
                payload = self._sub.get(timeout=0.1)
                if payload is None:
                    continue
                ds = serde.decode_dataset(payload)
                self.model.fit(ds)
                self.batches_fit += 1
                if self.score_topic is not None:
                    self.broker.publish(
                        self.score_topic,
                        np.float64(self.model.score()).tobytes())
        except Exception as e:
            self._error = e

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._error is not None:
            raise self._error
