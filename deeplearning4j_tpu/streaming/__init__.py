from . import serde
from .broker import Broker, InMemoryBroker, KafkaBroker, Subscription
from .pipelines import StreamingInferencePipeline, StreamingTrainingPipeline

__all__ = ["Broker", "InMemoryBroker", "KafkaBroker",
           "StreamingInferencePipeline", "StreamingTrainingPipeline",
           "Subscription", "serde"]
