"""Message transport for streaming pipelines.

TPU-native equivalent of reference dl4j-streaming's Kafka layer
(streaming/kafka/NDArrayPublisher.java, NDArrayConsumer.java over Camel
routes): a minimal Broker SPI with
- InMemoryBroker: in-process topics (the EmbeddedKafkaCluster role the
  reference uses in tests — SURVEY §4.6),
- KafkaBroker: real Kafka via kafka-python, import-gated (this image ships
  no Kafka client; the class raises a clear error at construction).
Payloads are opaque bytes; serde.py handles array/DataSet encoding.
"""
from __future__ import annotations

import queue
import threading


class Broker:
    def publish(self, topic, payload: bytes):
        raise NotImplementedError

    def subscribe(self, topic):
        """Returns a Subscription with get(timeout) -> bytes | None."""
        raise NotImplementedError


class Subscription:
    def __init__(self):
        self._q = queue.Queue()

    def get(self, timeout=None):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def _deliver(self, payload):
        self._q.put(payload)

    def drain(self):
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out


class InMemoryBroker(Broker):
    """Topic fan-out to every subscriber (Kafka consumer-group-per-
    subscription semantics, which is how the reference's routes use it)."""

    def __init__(self):
        self._subs = {}
        self._lock = threading.Lock()

    def publish(self, topic, payload):
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for s in subs:
            s._deliver(payload)

    def subscribe(self, topic):
        s = Subscription()
        with self._lock:
            self._subs.setdefault(topic, []).append(s)
        return s


class KafkaBroker(Broker):
    """Real Kafka transport (reference KafkaUriBuilder/NDArrayPublisher
    path). Requires the `kafka-python` package, which is not baked into
    this environment — constructing without it raises with instructions
    rather than failing deep inside a pipeline."""

    def __init__(self, bootstrap_servers="localhost:9092"):
        try:
            from kafka import KafkaConsumer, KafkaProducer  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "KafkaBroker needs the 'kafka-python' package; install it "
                "or use InMemoryBroker (the embedded-broker test "
                "transport)") from e
        from kafka import KafkaProducer
        self.bootstrap = bootstrap_servers
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)

    def publish(self, topic, payload):
        # async send — Kafka batches; flush() is explicit (a per-message
        # flush would serialize every publish behind a broker round-trip)
        self._producer.send(topic, payload)

    def flush(self):
        self._producer.flush()

    def close(self):
        self._producer.flush()
        self._producer.close()

    def subscribe(self, topic):
        from kafka import KafkaConsumer
        consumer = KafkaConsumer(topic, bootstrap_servers=self.bootstrap,
                                 auto_offset_reset="earliest")
        sub = Subscription()

        def pump():
            for msg in consumer:
                sub._deliver(msg.value)

        threading.Thread(target=pump, daemon=True).start()
        return sub
