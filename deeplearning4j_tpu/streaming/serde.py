"""Array / DataSet / record wire serde for streaming transport.

TPU-native equivalent of reference dl4j-streaming serde
(streaming/serde/RecordSerializer.java + conversion/NDArrayConverter — the
reference ships base64'd ND4J binary inside Camel messages). Here: npz bytes
for arrays and DataSets (the same container ModelSerializer/export use) and
UTF-8 CSV lines for records.
"""
from __future__ import annotations

import io

import numpy as np

from ..datasets.dataset import DataSet


def encode_array(arr) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, array=np.asarray(arr))
    return buf.getvalue()


def decode_array(payload: bytes):
    with np.load(io.BytesIO(payload)) as z:
        return z["array"]


def encode_dataset(ds: DataSet) -> bytes:
    buf = io.BytesIO()
    arrs = {"features": np.asarray(ds.features)}
    if ds.labels is not None:
        arrs["labels"] = np.asarray(ds.labels)
    if ds.features_mask is not None:
        arrs["features_mask"] = np.asarray(ds.features_mask)
    if ds.labels_mask is not None:
        arrs["labels_mask"] = np.asarray(ds.labels_mask)
    np.savez_compressed(buf, **arrs)
    return buf.getvalue()


def decode_dataset(payload: bytes) -> DataSet:
    with np.load(io.BytesIO(payload)) as z:
        return DataSet(z["features"],
                       z["labels"] if "labels" in z else None,
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)


def encode_record(values) -> bytes:
    return ",".join(str(float(v)) for v in values).encode("utf-8")


def decode_record(payload: bytes):
    return [float(v) for v in payload.decode("utf-8").split(",") if v]
