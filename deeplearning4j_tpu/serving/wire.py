"""Serving wire protocol: replicas behind a REAL process/socket
boundary.

PR 13's `FleetManager` proved the control loop — autoscale, canary,
crash survival — but every replica was an in-process object, so
"replica death" was a Python exception and "migration" a dict handoff.
This module is the transport half: the SAME router/failover/drain
machinery now runs against replicas that live behind a length-prefixed
TCP protocol, where a severed socket, a hung process, and a reaped
heartbeat are genuinely different failures from a raised exception.

Two halves, mirroring `parallel/ps_transport.py`'s server/client split
(whose framing, HELLO-identity, dedicated-heartbeat-socket, and
at-most-once-dedup discipline this module deliberately reuses — the
parameter-server lineage, Li et al. OSDI'14):

  * `ReplicaServer` — wraps ONE started `ContinuousDecodeServer`
    behind a listener. Every client frame carries a client-unique id;
    SUBMIT/MIGRATE_IN register the id in a request registry, so a
    RETRIED frame (reconnect after a lost ack) re-attaches to the
    original request instead of decoding twice (at-most-once — the PS
    transport's (worker, seq) dedup, generalized to string ids), and a
    finished request's result is RE-DELIVERED to the new connection.
    Results are pushed asynchronously as STREAM frames by a dedicated
    sender thread — a stalled client's TCP backpressure must never
    block the decode serve thread (whose done-callbacks only enqueue).
  * `RemoteReplica` — the client that plugs into `FleetManager`
    wherever an in-process `ContinuousDecodeServer` does: the same
    `submit/drain/migrate_in/kill/stop/alive/metrics` surface, with
    every verb crossing the wire. A broken connection reconnects under
    a `RetryPolicy` and RE-SENDS every unresolved in-flight frame
    (`wire_reconnects` / `wire_retries` counted); retry exhaustion
    marks the replica DEAD and fails every pending future with
    `ReplicaDeadError` — exactly the signal the manager's failover
    path replays prompts on. Liveness rides a DEDICATED heartbeat
    socket (the main socket legitimately stalls under big MIGRATE
    payloads): ack silence past `heartbeat_timeout` flips `alive`
    False and the manager's health probe reaps the replica — a HUNG
    process is reaped the same way a crashed one is.

Op table (each frame is `u32 len | u8 op | u32 hdr_len | hdr_json |
blob`; the blob carries artifact/param bytes, the JSON header
everything else):

    HELLO        identity + capabilities (instance, paged, block_size)
    SUBMIT       enqueue one decode request        -> ack, then STREAM
    STREAM       server-pushed result/error for a registered id
    CANCEL       drop interest in an id (purges the registry entry)
    DRAIN        drain(migrate=) the whole replica -> artifacts + specs
    MIGRATE_OUT  export one live request's KV state as artifact bytes
    MIGRATE_IN   adopt an artifact (tag-checked)   -> ack, then STREAM
    SNAPSHOT     kind_snapshot + alive + instance (metrics federation)
    SWAP         hot-swap params (leaves packed like a PS PUSH)
    HEARTBEAT    liveness ping (dedicated socket)
    STOP / KILL  graceful stop (drain semantics) / abrupt death

Failure classification over the wire (the fleet manager's verdict
table, serialized): an ERROR header names the exception class and the
client re-raises the REAL type — request-level verdicts
(`DeadlineExceededError`, `ServerOverloadedError`,
`UnhealthyOutputError`, `ValueError`) propagate to the caller's future
as-is; handoff markers (`RequestMigratedError`, `RequestDrainedError`)
mean the request's state moved; everything else — including an unknown
remote type (`WireRemoteError`) and every transport death — is
infrastructure, and the manager fails over by prompt replay
(deterministic greedy decode ⇒ the replayed stream is bit-identical
to an uninterrupted run). A destination that REFUSES a migration
(version tag, layout, overload) degrades to replay the same way:
correct bits either way, never a lost request.

Fault-injection sites (client side, `common.resilience.FaultInjector`):

    serve.wire.submit     fires between a SUBMIT's send and its ack —
                          a sever here IS the dropped-ACK scenario:
                          the server decoded, the ack died with the
                          connection, and the retried SUBMIT must
                          dedup (one decoded stream, one wire_retries)
    serve.wire.stream     fires as a STREAM frame arrives — a sever
                          drops the result mid-stream; reconnect +
                          re-SUBMIT re-delivers without re-decoding
    serve.wire.migrate    fires on DRAIN / MIGRATE_IN / MIGRATE_OUT
    serve.wire.heartbeat  fires per heartbeat tick — a repeated sever
                          is heartbeat SILENCE: `alive` decays and the
                          router reaps

Zero-dispatch pin: everything here is host-side socket plumbing — the
no-fault cross-process path adds ZERO device dispatches per token over
the same fleet in-process (tests/test_wire.py, dispatch-counter A/B).
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import itertools
import json
import logging
import os
import queue
import socket
import struct
import threading
import time

from ..common.resilience import (FaultInjected, RetryBudgetExhaustedError,
                                 RetryPolicy)
from .kvstate import (KVStateError, KVStateVersionError,
                      PrefixCacheArtifact, RequestArtifact)
from .server import (DeadlineExceededError, ReplicaDeadError,
                     RequestDrainedError, RequestMigratedError,
                     ServerClosedError, ServerOverloadedError,
                     ServingError, UnhealthyOutputError, _fail_future,
                     _ParamsView)

log = logging.getLogger(__name__)

__all__ = ["ReplicaServer", "RemoteReplica", "StaleEpochError",
           "WireProtocolError", "WireRemoteError", "run_replica_server"]

OP_HELLO = 1
OP_SUBMIT = 2
OP_STREAM = 3
OP_CANCEL = 4
OP_DRAIN = 5
OP_MIGRATE_OUT = 6
OP_MIGRATE_IN = 7
OP_SNAPSHOT = 8
OP_SWAP = 9
OP_HEARTBEAT = 10
OP_STOP = 11
OP_KILL = 12
OP_PREFIX_PULL = 13     # fleet prefix tier: export a resident chain
OP_PREFIX_PUSH = 14     # fleet prefix tier: adopt a peer's chain

# control-plane ops a stale-epoch manager is fenced out of (tentpole
# piece 3, ISSUE 16): everything that mutates the replica's lifecycle
# or params. Data-plane ops (SUBMIT/CANCEL/SNAPSHOT/MIGRATE_IN, the
# PREFIX tier) stay open — a zombie manager's in-flight REQUESTS still
# resolve; only its authority over the replica is revoked. The prefix
# ops are data-plane by the same rule: a pull moves CACHE bytes, never
# lifecycle or params, and a stale artifact is refused by its version
# tag, not by epoch fencing.
_FENCED_OPS = frozenset((OP_DRAIN, OP_MIGRATE_OUT, OP_SWAP,
                         OP_STOP, OP_KILL))


class WireProtocolError(ConnectionError):
    """Malformed/unexpected wire frame. Subclasses ConnectionError so
    a desynced stream is treated like a broken one: reconnect and
    re-run the (deduped) operations — the PS transport rule."""


class WireRemoteError(ServingError):
    """The replica reported an exception type this client does not
    know. Deliberately NOT a request-level verdict: the fleet
    manager's classification table treats it as infrastructure and
    fails over by prompt replay — an unknown failure must never be
    silently delivered as the request's outcome."""


class StaleEpochError(ServingError):
    """A control-plane op (DRAIN/SWAP/MIGRATE_OUT/STOP/KILL) arrived
    from a manager whose HELLO epoch is OLDER than the highest this
    replica has seen: a zombie predecessor trying to drive a fleet its
    successor owns. The replica refuses loudly (and counts
    `fenced_ops`) instead of obeying — the split-brain guard of the
    durable control plane (serving/fleetjournal.py)."""


# the exception types that survive a wire round-trip AS THEMSELVES —
# the fleet manager's verdict table depends on real types, so the
# ERROR header carries the class name and the client re-raises it
_WIRE_EXCEPTIONS = {cls.__name__: cls for cls in (
    ServingError, ServerOverloadedError, DeadlineExceededError,
    UnhealthyOutputError, ServerClosedError, ReplicaDeadError,
    RequestMigratedError, RequestDrainedError,
    KVStateError, KVStateVersionError, StaleEpochError)}
_WIRE_EXCEPTIONS["ValueError"] = ValueError


def _exc_to_hdr(exc):
    return {"error": type(exc).__name__, "message": str(exc)}


def _exc_from_hdr(hdr):
    cls = _WIRE_EXCEPTIONS.get(hdr.get("error"), WireRemoteError)
    msg = hdr.get("message", "")
    if cls is WireRemoteError:
        msg = f"{hdr.get('error')}: {msg}"
    return cls(msg)


# -- framing ----------------------------------------------------------------

def _close_sock(sock):
    """shutdown-then-close: a bare close() does NOT reliably wake a
    recv() blocked in another thread — shutdown(SHUT_RDWR) does, and
    the severed reader is exactly who must notice first."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock, n):
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _frame(op, hdr, blob=b""):
    h = json.dumps(hdr).encode()
    return struct.pack("<IBI", 5 + len(h) + len(blob), op, len(h)) \
        + h + blob


def _send_frame(sock, op, hdr, blob=b""):
    sock.sendall(_frame(op, hdr, blob))


def _recv_frame(sock):
    (length,) = struct.unpack("<I", _recv_exact(sock, 4))
    if length < 5:
        raise WireProtocolError(f"short frame ({length} bytes)")
    body = _recv_exact(sock, length)
    op = body[0]
    (hlen,) = struct.unpack_from("<I", body, 1)
    if 5 + hlen > length:
        raise WireProtocolError("frame header overruns frame")
    try:
        hdr = json.loads(body[5:5 + hlen].decode())
    except ValueError as e:
        raise WireProtocolError(f"bad frame header: {e}") from e
    return op, hdr, body[5 + hlen:]


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
class _Conn:
    __slots__ = ("sock", "wlock", "peer", "epoch")

    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()
        self.epoch = None    # manager epoch announced by this
        #                      connection's HELLO (None = legacy
        #                      client, unfenced)
        try:
            self.peer = sock.getpeername()
        except OSError:
            self.peer = None

    def send(self, op, hdr, blob=b""):
        with self.wlock:
            # graftlint: disable=lock-discipline -- wlock is this connection's dedicated write mutex (frame interleaving guard); it never nests another lock and a stalled peer blocks only this connection's writers
            _send_frame(self.sock, op, hdr, blob)


class _Entry:
    """One registered request: the decode server's future plus the
    connection its STREAM frame should land on, re-pointed by retried
    frames. `attempt` orders the re-pointing: the client bumps it per
    resend, and only an equal-or-NEWER attempt may move delivery — a
    STALE original frame (still buffered on the severed connection,
    read after the retry landed on the fresh one) must never point the
    result back at the dead socket."""

    __slots__ = ("rid", "future", "conn", "attempt")

    def __init__(self, rid, future, conn, attempt=0):
        self.rid = rid
        self.future = future
        self.conn = conn
        self.attempt = attempt


class ReplicaServer:
    """Socket front end over one `ContinuousDecodeServer` (module
    docstring: op table, dedup registry, async delivery).

    `server` may be started or not (the wrapper starts it). The
    listener binds `host:port` (port 0 = ephemeral; read `.port`).
    In-thread use (tests, same-process fleets over a real loopback
    wire) keeps a handle to both; cross-process use runs
    `run_replica_server` in the child and talks only through
    `RemoteReplica`."""

    # completed registry entries kept for re-delivery; beyond this the
    # oldest DONE entries are pruned (a client that never reconnects
    # must not grow the registry without bound)
    _REGISTRY_CAP = 4096

    def __init__(self, server, host="127.0.0.1", port=0):
        self.server = server
        if not server._running and not getattr(server, "_killed", False):
            server.start()
        self._lock = threading.Lock()
        self._registry = collections.OrderedDict()   # rid -> _Entry
        self._rpc_cache = collections.OrderedDict()  # rid -> reply frame
        self._rpc_cache_bytes = 0
        self._client_ids = itertools.count()
        self._closed = False
        self.killed = False
        self.epoch_seen = 0   # highest manager epoch HELLO'd to this
        #   replica; control frames from an older epoch are fenced
        self._start_time = time.time()   # wire-front-end birth: the
        #   identity re-adoption verifies alongside pid, so a recycled
        #   port owned by a DIFFERENT incarnation is refused
        self.pause_heartbeats = False    # chaos hook: a HUNG process —
        #   the main socket still answers but liveness goes silent, and
        #   the client's heartbeat-timeout reap is the only way out
        self._stop_evt = threading.Event()
        self._sendq = queue.Queue()
        self._sender = threading.Thread(target=self._send_loop,
                                        name="wire-sender", daemon=True)
        self._sender.start()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="wire-accept", daemon=True)
        self._accept.start()

    # -- lifecycle -----------------------------------------------------
    def serve_forever(self, timeout=None):
        """Block until a STOP/KILL/DRAIN frame shuts the replica down
        (the cross-process child's main loop). Returns True when the
        shutdown was a graceful one (trace-saving is appropriate),
        False after KILL (a crash persists nothing)."""
        self._stop_evt.wait(timeout)
        self.close(stop_server=False)    # STOP/DRAIN already stopped it
        return not self.killed

    def close(self, stop_server=True):
        """Tear the wire front end down (listener + sender); with
        `stop_server`, also stop the decode server underneath."""
        self._closed = True
        self._stop_evt.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._sendq.put(None)            # unblock the sender
        if stop_server and self.server._running:
            try:
                self.server.stop(drain=True)
            except Exception:   # noqa: BLE001 — teardown finishes
                log.exception("decode server stop failed at close()")

    # -- delivery ------------------------------------------------------
    def _send_loop(self):
        """THE delivery thread: future done-callbacks (which run on the
        decode serve thread) only ever enqueue here — a stalled
        client's TCP backpressure can never block an iteration."""
        while True:
            item = self._sendq.get()
            try:
                if item is None:
                    return
                conn, op, hdr, blob = item
                try:
                    conn.send(op, hdr, blob)
                except OSError:
                    # client gone: the result stays in the registry
                    # and is re-delivered when the reconnecting client
                    # re-SUBMITs
                    pass
            finally:
                # every item is accounted (sentinel included) so the
                # STOP handler's join() below can never deadlock
                self._sendq.task_done()

    def _stream_frame(self, entry):
        fut = entry.future
        if fut.cancelled():
            hdr = {"id": entry.rid, "error": "CancelledError",
                   "message": "request cancelled on the replica"}
        else:
            exc = fut.exception()
            if exc is not None:
                hdr = dict(_exc_to_hdr(exc), id=entry.rid)
            else:
                hdr = {"id": entry.rid,
                       "tokens": [int(t) for t in fut.result()]}
        return hdr

    def _queue_delivery(self, entry):
        conn = entry.conn
        if conn is None:
            return
        self._sendq.put((conn, OP_STREAM, self._stream_frame(entry), b""))

    def _register_or_dedup(self, rid, conn, call, attempt=0):
        """ATOMIC dedup-or-create: the registry lookup, the decode
        submit, and the insert happen under ONE lock — a retried frame
        racing the original's handler thread (read the frame, about to
        submit) must block here and then find the entry, never
        double-submit. Returns (entry, created, exc): a synchronous
        verdict from `call` comes back as `exc` with nothing
        registered."""
        with self._lock:
            entry = self._registry.get(rid)
            if entry is not None:
                return entry, False, None
            try:
                future = call()
            except BaseException as e:  # noqa: BLE001 — verdict crosses
                return None, False, e
            entry = _Entry(rid, future, conn, attempt=attempt)
            self._registry[rid] = entry
            # prune: oldest DONE entries beyond the cap (generator, no
            # full-dict copy — this runs under the dispatch lock on
            # every insert once the cap is reached)
            while len(self._registry) > self._REGISTRY_CAP:
                victim = next((k for k, e in self._registry.items()
                               if e.future.done()), None)
                if victim is None:
                    break
                del self._registry[victim]
        future.add_done_callback(lambda _f: self._queue_delivery(entry))
        return entry, True, None

    def _dedup_repoint(self, entry, conn, attempt, op):
        """The dedup branch's delivery half: an equal-or-newer attempt
        re-points delivery at this connection and re-pushes a finished
        result; a STALE frame only gets its (harmless) dup-ack."""
        with self._lock:
            if attempt >= entry.attempt:
                entry.attempt = attempt
                entry.conn = conn
                repoint = True
            else:
                repoint = False
        try:
            conn.send(op, {"id": entry.rid, "ok": True, "dup": True})
        except OSError:
            pass    # a stale frame's conn is usually already dead
        if repoint and entry.future.done():
            self._queue_delivery(entry)

    # -- connection handling -------------------------------------------
    def _accept_loop(self):
        while True:
            try:
                sock, _ = self._sock.accept()
            except OSError:              # listener closed
                return
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            t = threading.Thread(target=self._serve_conn,
                                 args=(_Conn(sock),),
                                 name="wire-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn):
        try:
            with conn.sock:
                while not self._closed:
                    try:
                        op, hdr, blob = _recv_frame(conn.sock)
                    except (ConnectionError, OSError):
                        return
                    if not self._dispatch(conn, op, hdr, blob):
                        return
        except Exception:   # noqa: BLE001 — one bad client never kills serve
            log.exception("wire connection handler failed")

    def _reply_cached(self, conn, rid):
        with self._lock:
            frame = self._rpc_cache.get(rid)
        if frame is None:
            return False
        with conn.wlock:
            # graftlint: disable=lock-discipline -- the _Conn.send write-mutex rule: cached frames bypass the header re-encode but must still serialize with the sender thread's frames on this socket
            conn.sock.sendall(frame)
        return True

    # the rpc reply cache is bounded by BYTES as well as count:
    # MIGRATE_OUT/DRAIN replies embed whole KV-panel blobs (the blob
    # is load-bearing — a retried op after a lost ack can only get the
    # artifact from here, the request already left the slot), so a
    # count-only cap would pin arbitrarily many megabytes on a
    # long-lived replica under migration churn
    _RPC_CACHE_MAX = 256
    _RPC_CACHE_MAX_BYTES = 32 << 20

    def _cache_reply(self, rid, op, hdr, blob=b""):
        frame = _frame(op, hdr, blob)
        with self._lock:
            self._rpc_cache[rid] = frame
            self._rpc_cache_bytes += len(frame)
            while self._rpc_cache and len(self._rpc_cache) > 1 and (
                    len(self._rpc_cache) > self._RPC_CACHE_MAX
                    or self._rpc_cache_bytes
                    > self._RPC_CACHE_MAX_BYTES):
                _, old = self._rpc_cache.popitem(last=False)
                self._rpc_cache_bytes -= len(old)
        return frame

    def _dispatch(self, conn, op, hdr, blob):
        """Handle one frame; returns False to close the connection."""
        srv = self.server
        rid = hdr.get("id")
        if op == OP_HELLO:
            cid = hdr.get("client_id")
            if not cid:
                cid = f"c{next(self._client_ids)}"
            epoch = hdr.get("epoch")
            if epoch is not None:
                conn.epoch = int(epoch)
                with self._lock:
                    delta = max(0, conn.epoch - self.epoch_seen)
                    self.epoch_seen = max(self.epoch_seen, conn.epoch)
                if delta:
                    # the manager_epoch counter IS the highest manager
                    # generation served (bumped by delta: monotone,
                    # fleet-summable, equals epoch_seen)
                    try:
                        srv.metrics.count("manager_epoch", delta)
                    except Exception:  # noqa: BLE001 — counting never
                        pass           # breaks the handshake
            conn.send(OP_HELLO, {
                "client_id": cid,
                "instance": getattr(srv, "instance", None),
                "pid": os.getpid(),
                "start_time": self._start_time,
                "epoch": self.epoch_seen,
                "paged": bool(getattr(srv, "paged", False)),
                "block_size": getattr(srv, "_block_size", None)})
            return True
        if op == OP_HEARTBEAT:
            if not self.pause_heartbeats:
                conn.send(OP_HEARTBEAT, {"id": rid, "ok": True})
            return True
        if op in _FENCED_OPS and conn.epoch is not None \
                and conn.epoch < self.epoch_seen:
            # epoch fence: refuse loudly with the typed error — the
            # stale manager's caller re-raises StaleEpochError and its
            # degrade paths (replay, crash accounting) keep every
            # request; obeying would hand the replica to a zombie
            try:
                srv.metrics.count("fenced_ops")
            except Exception:   # noqa: BLE001 — counting never fences
                pass
            conn.send(op, dict(_exc_to_hdr(StaleEpochError(
                f"op {op} refused: connection epoch {conn.epoch} < "
                f"highest seen {self.epoch_seen}")), id=rid))
            return True
        if op == OP_SUBMIT:
            attempt = int(hdr.get("attempt", 0))
            entry, created, err = self._register_or_dedup(
                rid, conn,
                lambda: srv.submit(hdr["prompt"], hdr["max_new"],
                                   deadline_ms=hdr.get("deadline_ms"),
                                   klass=hdr.get("klass", "default")),
                attempt=attempt)
            if err is not None:
                conn.send(OP_SUBMIT, dict(_exc_to_hdr(err), id=rid))
                return True
            if not created:
                # the at-most-once rule: a retried SUBMIT after a lost
                # ack re-attaches — never decodes twice — and an
                # equal-or-newer attempt re-points delivery + re-pushes
                # a finished result (a STALE original frame read off
                # the severed connection AFTER the retry must not point
                # the result back at the dead socket)
                self._dedup_repoint(entry, conn, attempt, OP_SUBMIT)
                return True
            conn.send(OP_SUBMIT, {"id": rid, "ok": True})
            return True
        if op == OP_STREAM:
            return True                  # clients never push streams
        if op == OP_CANCEL:
            with self._lock:
                entry = self._registry.pop(rid, None)
            if entry is not None:
                entry.conn = None        # drop delivery interest
                entry.future.cancel()    # no-op once running
            conn.send(OP_CANCEL, {"id": rid, "ok": True})
            return True
        if op == OP_MIGRATE_IN:
            def _adopt():
                art = RequestArtifact.from_bytes(blob)
                return srv.migrate_in(art,
                                      deadline_ms=hdr.get("deadline_ms"))
            attempt = int(hdr.get("attempt", 0))
            entry, created, err = self._register_or_dedup(
                rid, conn, _adopt, attempt=attempt)
            if err is not None:
                if self._reply_cached(conn, rid):
                    return True     # cached REFUSAL (retried blob-less
                    #                 frame re-raised locally — the
                    #                 first verdict stands)
                reply = dict(_exc_to_hdr(err), id=rid)
                self._cache_reply(rid, OP_MIGRATE_IN, reply)
                conn.send(OP_MIGRATE_IN, reply)
                return True
            if not created:
                # retried MIGRATE_IN after a lost ack: the SUBMIT dedup
                # rule — attempt-ordered re-point + re-push (a cached
                # reply alone would strand the stream on a dead socket)
                self._dedup_repoint(entry, conn, attempt, OP_MIGRATE_IN)
                return True
            conn.send(OP_MIGRATE_IN, {"id": rid, "ok": True})
            return True
        if op == OP_MIGRATE_OUT:
            if self._reply_cached(conn, rid):
                return True
            with self._lock:
                entry = self._registry.get(hdr.get("rid"))
            try:
                if entry is None:
                    raise KVStateError(
                        f"no request {hdr.get('rid')!r} on this replica")
                art = srv.migrate_out(entry.future,
                                      timeout=hdr.get("timeout", 30.0))
            except BaseException as e:  # noqa: BLE001
                reply = dict(_exc_to_hdr(e), id=rid)
                self._cache_reply(rid, OP_MIGRATE_OUT, reply)
                conn.send(OP_MIGRATE_OUT, reply)
                return True
            data = art.to_bytes()
            self._cache_reply(rid, OP_MIGRATE_OUT,
                              {"id": rid, "ok": True}, data)
            conn.send(OP_MIGRATE_OUT, {"id": rid, "ok": True}, data)
            return True
        if op == OP_PREFIX_PULL:
            # fleet prefix tier, SOURCE side: ship the resident chain
            # covering the requested key. Idempotent and side-effect
            # free on this replica (the blocks stay resident), so no
            # reply cache — a retried pull just re-extracts.
            try:
                art = srv.prefix_export(
                    tuple(hdr.get("key") or ()),
                    max_bytes=hdr.get("max_bytes"),
                    timeout=hdr.get("timeout", 30.0))
            except BaseException as e:  # noqa: BLE001 — verdict crosses
                conn.send(OP_PREFIX_PULL, dict(_exc_to_hdr(e), id=rid))
                return True
            if art is None:
                conn.send(OP_PREFIX_PULL,
                          {"id": rid, "ok": True, "found": False})
                return True
            conn.send(OP_PREFIX_PULL,
                      {"id": rid, "ok": True, "found": True},
                      art.to_bytes())
            return True
        if op == OP_PREFIX_PUSH:
            # fleet prefix tier, SINK side: adopt a peer's exported
            # chain. Idempotent too — an already-indexed key adopts
            # zero blocks — and the refusal verdict (version tag) is
            # recomputed identically on a retry, so no reply cache.
            try:
                art = PrefixCacheArtifact.from_bytes(blob)
                n = srv.prefix_adopt(art,
                                     timeout=hdr.get("timeout", 30.0))
            except BaseException as e:  # noqa: BLE001 — verdict crosses
                conn.send(OP_PREFIX_PUSH, dict(_exc_to_hdr(e), id=rid))
                return True
            conn.send(OP_PREFIX_PUSH,
                      {"id": rid, "ok": True, "adopted": int(n)})
            return True
        if op == OP_SNAPSHOT:
            conn.send(OP_SNAPSHOT, {
                "id": rid,
                "snapshot": srv.metrics.kind_snapshot(),
                "alive": bool(srv.alive),
                "instance": getattr(srv, "instance", None)})
            return True
        if op == OP_SWAP:
            if self._reply_cached(conn, rid):
                return True
            try:
                self._apply_swap(blob)
            except BaseException as e:  # noqa: BLE001 — verdict crosses
                reply = dict(_exc_to_hdr(e), id=rid)
                self._cache_reply(rid, OP_SWAP, reply)
                conn.send(OP_SWAP, reply)
                return True
            reply = {"id": rid, "ok": True}
            self._cache_reply(rid, OP_SWAP, reply)
            conn.send(OP_SWAP, reply)
            return True
        if op == OP_DRAIN:
            if self._reply_cached(conn, rid):
                return True
            reply_hdr, reply_blob = self._do_drain(hdr)
            self._cache_reply(rid, OP_DRAIN, reply_hdr, reply_blob)
            conn.send(OP_DRAIN, reply_hdr, reply_blob)
            if reply_hdr.get("ok"):
                self._stop_evt.set()     # a drained replica is done
            return True
        if op == OP_STOP:
            try:
                srv.stop(drain=bool(hdr.get("drain", True)),
                         timeout=hdr.get("timeout"))
            except Exception:   # noqa: BLE001 — stop must ack anyway
                log.exception("decode server stop failed over the wire")
            # drained results enqueue via done-callbacks during stop();
            # flush them BEFORE the ack — returning False closes this
            # connection, and an unflushed STREAM frame would fail a
            # future the replica already resolved
            self._sendq.join()
            conn.send(OP_STOP, {"id": rid, "ok": True})
            self._stop_evt.set()
            return False
        if op == OP_KILL:
            self.killed = True
            try:
                srv.kill()
            finally:
                self._stop_evt.set()
            try:
                conn.send(OP_KILL, {"id": rid, "ok": True})
            except OSError:
                pass
            return False
        raise WireProtocolError(f"unknown op {op}")

    def _apply_swap(self, blob):
        import jax

        from ..parallel.ps_transport import unpack_leaves
        cur = self.server.current_params()
        treedef = jax.tree_util.tree_structure(cur)
        leaves, _ = unpack_leaves(blob)
        aux, blocks = jax.tree_util.tree_unflatten(treedef, leaves)
        self.server.swap(_ParamsView(aux, blocks))

    def _do_drain(self, hdr):
        srv = self.server
        try:
            migrated, replayed = srv.drain(
                migrate=hdr.get("migrate"),
                timeout=hdr.get("timeout", 60.0))
        except BaseException as e:  # noqa: BLE001 — degrade to crash
            return dict(_exc_to_hdr(e), id=hdr.get("id")), b""
        with self._lock:
            by_fut = {e.future: r for r, e in self._registry.items()}
        now = time.monotonic()
        m_out, blobs = [], []
        for fut, art in migrated:
            data = art.to_bytes()
            m_out.append({"rid": by_fut.get(fut), "nbytes": len(data)})
            blobs.append(data)
        r_out = []
        for fut, spec in replayed:
            dl = spec.get("deadline")
            r_out.append({"rid": by_fut.get(fut),
                          "spec": {"prompt": spec["prompt"],
                                   "max_new": spec["max_new"],
                                   "deadline_ms": (None if dl is None
                                                   else max(0.0, (dl - now)
                                                            * 1e3)),
                                   "klass": spec.get("klass", "default")}})
        # flush queued STREAM deliveries BEFORE the reply goes out: a
        # request that finished just ahead of the drain has its result
        # sitting in the send queue, and the reply overtaking it would
        # make the client tear down (and the manager re-decode) a
        # stream the replica already resolved — the OP_STOP rule
        self._sendq.join()
        return ({"id": hdr.get("id"), "ok": True,
                 "migrated": m_out, "replayed": r_out},
                b"".join(blobs))


def run_replica_server(server, host="127.0.0.1", port=0, port_file=None,
                       tracer=None, trace_out=None, identity_file=None):
    """The cross-process child's main: wrap `server` in a
    `ReplicaServer`, publish the bound port (atomically — a parent
    polls for the file), serve until STOP/KILL/DRAIN, and save the
    tracer's Chrome trace on a GRACEFUL exit (a KILLed replica
    persists nothing — a real crash would not). Returns the wrapper.

    `identity_file` additionally publishes the replica's wire identity
    (host/port/pid/instance/start_time/epoch) as atomic JSON and
    REMOVES it on a graceful exit — so a recovering manager can tell a
    cleanly-stopped replica (file gone: nothing to re-adopt) from a
    crashed or orphaned one (file present: dial and verify)."""
    rs = ReplicaServer(server, host=host, port=port)
    if port_file:
        tmp = str(port_file) + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(str(rs.port))
        os.replace(tmp, str(port_file))
    if identity_file:
        tmp = str(identity_file) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"host": rs.host, "port": rs.port,
                       "pid": os.getpid(),
                       "instance": getattr(server, "instance", None),
                       "start_time": rs._start_time,
                       "epoch": rs.epoch_seen}, fh)
        os.replace(tmp, str(identity_file))
    graceful = rs.serve_forever()
    if graceful and tracer is not None and trace_out:
        try:
            tracer.save(str(trace_out))
        except Exception:   # noqa: BLE001 — trace is best-effort
            log.exception("trace save failed at replica shutdown")
    if graceful and identity_file:
        try:
            os.remove(str(identity_file))
        except OSError:
            pass    # already gone: the distinguishing bit is absence
    return rs


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------
class _PendingOp:
    __slots__ = ("rid", "op", "hdr", "blob", "ack", "stream", "resend",
                 "attempt", "sent")

    def __init__(self, rid, op, hdr, blob=b"", stream=False, resend=True):
        self.rid = rid
        self.op = op
        self.hdr = hdr
        self.blob = blob
        self.ack = cf.Future()           # resolves (hdr, blob) or exc
        self.stream = cf.Future() if stream else None
        self.resend = resend
        self.attempt = 0                 # bumped per re-sent frame: the
        #                                  server's attempt-ordered
        #                                  delivery re-pointing
        self.sent = False                # first send attempted — only
        #                                  then is the op eligible for
        #                                  reconnect resends (a lazy
        #                                  dial inside _send_op must
        #                                  not resend the op that call
        #                                  is about to send)

    @property
    def done(self):
        if self.stream is not None:
            return self.stream.done()
        return self.ack.done()


class _RemoteMetrics:
    """The `ServingMetrics`-shaped facade the fleet plane reads off a
    remote replica: `kind_snapshot()` fetches fresh state over the
    wire (falling back to the last good snapshot when the wire is
    down — exactly what the manager's counters-only TOMBSTONE needs to
    stay monotone after a death), while `count_value()` reads the
    CACHE only, so the per-tick health probe never multiplies wire
    round-trips by counter key."""

    def __init__(self, replica):
        self._replica = replica
        self._cache = {}

    @property
    def instance(self):
        return self._replica.instance

    @property
    def name(self):
        return self._replica.instance

    def kind_snapshot(self):
        try:
            self._cache = self._replica._fetch_snapshot()
        except Exception:   # noqa: BLE001 — stale beats absent
            pass
        return dict(self._cache)

    def count_value(self, key):
        m = self._cache.get(key)
        if isinstance(m, dict) and m.get("kind") == "counter":
            return m.get("value") or 0
        return 0

    def snapshot(self):
        """The familiar flat snapshot() shape, derived from the latest
        kind snapshot (histograms/summaries as _p50/_p99/_mean/_count
        — `FleetView.flat`'s flattening, reused)."""
        from ..obs.fleet import FleetView
        name = self.instance or "remote"
        return FleetView().add(name, self.kind_snapshot()).flat(name)


class RemoteReplica:
    """`FleetManager`-pluggable client for one `ReplicaServer` (module
    docstring: reconnect-with-resend, heartbeat liveness, failure
    classification).

    `process` (optional) is a Popen-like handle this replica OWNS: its
    exit flips `alive`, `kill()` terminates it, and `stop()` waits for
    it. `counters` is any object with `.count(key, n)` — the fleet
    manager binds its own `ServingMetrics` via `configure_wire()` so
    `wire_reconnects`/`wire_retries` land on the fleet's control-plane
    snapshot."""

    def __init__(self, host, port, name=None, retry_policy=None,
                 heartbeat_interval=0.25, heartbeat_timeout=None,
                 fault_injector=None, counters=None, process=None,
                 connect_timeout=30.0, op_timeout=120.0):
        self._host = host
        self._port = int(port)
        self.name = name
        self.instance = name
        self._retry_is_default = retry_policy is None
        self._retry = retry_policy if retry_policy is not None else \
            RetryPolicy(max_retries=3, base_delay=0.05, max_delay=0.5,
                        jitter=0.0)
        self._injector = fault_injector
        self._counters = counters
        self._process = process
        self._connect_timeout = float(connect_timeout)
        self._op_timeout = float(op_timeout)
        self._client_id = None
        self._paged = False
        self._block_size = None
        self._epoch = None    # manager epoch announced in HELLO once
        #                       configure_wire(epoch=) sets it
        self.pid = None       # replica identity off the HELLO reply:
        self.start_time = None   # recovery verifies these against the
        #                       journal before re-adopting a port
        self._ids = itertools.count()
        self._pending = {}               # rid -> _PendingOp
        self._plock = threading.Lock()
        self._wlock = threading.Lock()   # serializes main-socket sends
        self._conn_lock = threading.RLock()
        self._rc_lock = threading.Lock()  # one reconnector at a time
        self._sock = None
        self._gen = 0
        self._ever_connected = False
        self._dead = False
        self._dead_exc = None
        self._closed = False
        self._running = True             # the fleet-manager contract
        self.metrics = _RemoteMetrics(self)
        # heartbeat state: a dedicated socket, like the PS client's —
        # the main socket legitimately stalls under MIGRATE payloads
        self._hb_interval = (None if not heartbeat_interval
                             else float(heartbeat_interval))
        self.heartbeat_timeout = (None if heartbeat_timeout is None
                                  else float(heartbeat_timeout))
        self._hb_last_ok = time.monotonic()
        self._hb_stop = threading.Event()
        self._hb_thread = None
        # first dial is LOUD: an unreachable replica fails the factory
        self._retry.call(self._dial_once)
        if self._hb_interval:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, name="wire-heartbeat",
                daemon=True)
            self._hb_thread.start()

    # -- fleet-manager surface ----------------------------------------
    def start(self):
        return self

    def configure_wire(self, heartbeat_timeout=None, retry_policy=None,
                       counters=None, epoch=None):
        """Fleet-manager hook (`FleetManager._spawn`): fill in
        fleet-level wire config the factory left unset — the manager's
        `heartbeat_timeout`, its failover `RetryPolicy`, its
        `ServingMetrics` as the wire-counter sink, and its `epoch`
        (announced to the replica so stale-manager fencing engages)."""
        if counters is not None:
            self._counters = counters
        if retry_policy is not None and self._retry_is_default:
            # only replace the built-in default, never an explicit one
            self._retry = retry_policy
            self._retry_is_default = False
        if heartbeat_timeout is not None and \
                self.heartbeat_timeout is None:
            self.heartbeat_timeout = float(heartbeat_timeout)
        if epoch is not None and epoch != self._epoch:
            self._epoch = int(epoch)
            self._announce_epoch()
        return self

    def _announce_epoch(self):
        """Best-effort re-HELLO on the LIVE main connection with the
        newly configured epoch (future dials carry it in their opening
        HELLO). The reply matches no pending op and falls through
        `_on_reply` harmlessly — only the server-side `epoch_seen`
        bump matters."""
        try:
            with self._conn_lock:
                sock = self._sock
            if sock is None:
                return    # the next dial's HELLO announces it
            with self._wlock:
                # graftlint: disable=lock-discipline -- _wlock is the main socket's dedicated write mutex (the _send_op rule); it never nests another lock
                _send_frame(sock, OP_HELLO,
                            {"client_id": self._client_id,
                             "epoch": self._epoch})
        except OSError:
            pass    # broken wire: the reconnect dial re-announces

    @property
    def paged(self):
        return self._paged

    @property
    def alive(self):
        """The router's liveness probe: dead wire, exited process, or
        heartbeat-ack silence past `heartbeat_timeout` all read False
        — the healthy→degraded→dead state machine's input."""
        if self._dead or self._closed:
            return False
        if self._process is not None and self._process.poll() is not None:
            return False
        if self.heartbeat_timeout is not None and self._hb_interval:
            return (time.monotonic() - self._hb_last_ok
                    <= self.heartbeat_timeout)
        return True

    def current_params(self):
        raise NotImplementedError(
            "a remote replica's params live in its own process; swap() "
            "ships new ones, but there is no params pull op (canary "
            "rollout is in-process-only until the sharding round)")

    def submit(self, prompt, max_new_tokens, deadline_ms=None,
               klass="default"):
        """Enqueue one decode request over the wire; returns a future
        resolving to the full token list. Synchronous verdicts at the
        replica (sheds, closed) re-raise here with their REAL types —
        the local submit contract, preserved across the wire."""
        self._check_usable()
        rid = self._mint()
        hdr = {"id": rid, "prompt": [int(t) for t in prompt],
               "max_new": int(max_new_tokens),
               "deadline_ms": deadline_ms, "klass": klass}
        p = _PendingOp(rid, OP_SUBMIT, hdr, stream=True)
        try:
            self._send_op(p, site="serve.wire.submit")
            self._await_ack(p)
        except BaseException:
            self._forget(rid)
            raise
        return p.stream

    def generate(self, prompt, max_new_tokens, deadline_ms=None,
                 timeout=None):
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    def migrate_in(self, artifact, deadline_ms=None):
        """Ship an artifact to the replica (`to_bytes` over the wire,
        tag-checked at the far end). Refusals — version tag, layout,
        overload — re-raise synchronously with their real types, so
        the manager's degrade-to-replay path works unchanged."""
        self._check_usable()
        rid = self._mint()
        p = _PendingOp(rid, OP_MIGRATE_IN,
                       {"id": rid, "deadline_ms": deadline_ms},
                       blob=artifact.to_bytes(), stream=True)
        try:
            self._send_op(p, site="serve.wire.migrate")
            self._await_ack(p)
        except BaseException:
            self._forget(rid)
            raise
        return p.stream

    def migrate_out(self, future, timeout=30.0):
        """Export a live request by its submit() future; returns the
        `RequestArtifact` (the local future fails RequestMigratedError
        via the replica's STREAM push, same as in-process)."""
        with self._plock:
            rid = next((r for r, p in self._pending.items()
                        if p.stream is future), None)
        if rid is None:
            raise KVStateError("future was not submitted through this "
                               "replica")
        oid = self._mint()
        p = _PendingOp(oid, OP_MIGRATE_OUT,
                       {"id": oid, "rid": rid, "timeout": timeout})
        try:
            self._send_op(p, site="serve.wire.migrate")
            hdr, blob = self._await_ack(p, timeout + self._op_timeout)
        except BaseException:
            # a failed op must leave the registry: an unresolved
            # pending entry is excluded from the done-op prune AND
            # re-sent on every later reconnect, forever (graftlint
            # future-hygiene triage, ISSUE 15)
            self._forget(oid)
            raise
        return RequestArtifact.from_bytes(blob)

    def prefix_export(self, key, max_bytes=None, timeout=30.0):
        """Pull the replica's resident prefix chain covering `key` as
        a `PrefixCacheArtifact` (None when nothing is resident) — the
        wire twin of `ContinuousDecodeServer.prefix_export`, so the
        fleet prefix tier drives in-process and remote replicas
        through one seam."""
        self._check_usable()
        rid = self._mint()
        p = _PendingOp(rid, OP_PREFIX_PULL,
                       {"id": rid, "key": [int(t) for t in key],
                        "max_bytes": max_bytes, "timeout": timeout})
        try:
            self._send_op(p, site="serve.wire.migrate")
            hdr, blob = self._await_ack(p, timeout + self._op_timeout)
        except BaseException:
            self._forget(rid)
            raise
        if not hdr.get("found"):
            return None
        return PrefixCacheArtifact.from_bytes(blob)

    def prefix_adopt(self, artifact, timeout=30.0):
        """Ship a peer's exported prefix chain into this replica
        (`to_bytes` over the wire, tag-checked at the far end — a
        `KVStateVersionError` refusal re-raises here with its real
        type so the manager can count it and fall back to cold
        compute). Returns the number of blocks adopted."""
        self._check_usable()
        rid = self._mint()
        p = _PendingOp(rid, OP_PREFIX_PUSH, {"id": rid,
                                             "timeout": timeout},
                       blob=artifact.to_bytes())
        try:
            self._send_op(p, site="serve.wire.migrate")
            hdr, _blob = self._await_ack(p, timeout + self._op_timeout)
        except BaseException:
            self._forget(rid)
            raise
        return int(hdr.get("adopted", 0))

    def drain(self, migrate=None, timeout=60.0):
        """The fleet drain verb over the wire: returns ``(migrated,
        replayed)`` in exactly `ContinuousDecodeServer.drain`'s shape —
        each entry's future is THIS client's future for that request,
        so `FleetManager.scale_down` repoints artifacts and replays
        specs with no remote-special code path. The replica stops
        itself after draining; this side closes too."""
        self._check_usable()
        rid = self._mint()
        p = _PendingOp(rid, OP_DRAIN,
                       {"id": rid, "migrate": migrate, "timeout": timeout})
        try:
            self._send_op(p, site="serve.wire.migrate")
            hdr, blob = self._await_ack(p, timeout + self._op_timeout)
        except BaseException:
            self._forget(rid)   # the migrate_out rule: a failed op
            raise               # must never linger for resend
        migrated, replayed = [], []
        off = 0
        for m in hdr.get("migrated", ()):
            data = blob[off:off + m["nbytes"]]
            off += m["nbytes"]
            art = RequestArtifact.from_bytes(data)
            fut = self._future_for(m.get("rid"), RequestMigratedError(
                "request drained to another replica"))
            migrated.append((fut, art))
        for r in hdr.get("replayed", ()):
            spec = dict(r["spec"])
            # the wire spec carries REMAINING deadline ms; re-anchor it
            # on this side's clock (the local drain contract: absolute
            # monotonic or None)
            dl = spec.pop("deadline_ms", None)
            spec["deadline"] = (None if dl is None
                                else time.monotonic() + dl / 1e3)
            fut = self._future_for(r.get("rid"), RequestDrainedError(
                "request replayed on another replica"))
            replayed.append((fut, spec))
        self._shutdown_local(ServerClosedError("replica drained"),
                             dead=False)
        self._reap_process(timeout)
        return migrated, replayed

    def swap(self, new_lm):
        """Hot-swap the replica's params: (aux, blocks) leaves packed
        like a PS PUSH (both ends hold the same model, so only leaves
        cross the wire). Structure/shape refusals re-raise as
        ValueError — the local swap contract."""
        import numpy as np

        import jax

        from ..parallel.ps_transport import pack_leaves
        self._check_usable()
        leaves = [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(
            (new_lm.aux, new_lm.blocks))]
        rid = self._mint()
        p = _PendingOp(rid, OP_SWAP, {"id": rid},
                       blob=pack_leaves(leaves))
        try:
            self._send_op(p)
            self._await_ack(p)
        except BaseException:
            self._forget(rid)   # the migrate_out rule: a failed op
            raise               # must never linger for resend

    def kill(self):
        """Abrupt replica death from this side: best-effort KILL frame
        (a severed wire may never deliver it), then every pending
        future fails loudly with `ReplicaDeadError` and the owned
        process is terminated — the fleet crash verb, cross-process."""
        if self._closed and self._dead:
            return
        self._dead = True
        try:
            with self._conn_lock:
                sock = self._sock
            if sock is not None:
                # BOUND the best-effort frame: a peer with a full TCP
                # buffer would otherwise block this sendall forever —
                # kill() is the fleet's crash verb and must never
                # wedge on the replica it is crashing. Disturbing the
                # reader thread with the timeout is fine: the local
                # teardown below severs this socket anyway.
                sock.settimeout(5.0)
                with self._wlock:
                    # graftlint: disable=lock-discipline -- best-effort frame on the shared write mutex, bounded by the settimeout above; the teardown below severs the socket regardless
                    _send_frame(sock, OP_KILL, {"id": self._mint()})
        except OSError:
            pass
        self._shutdown_local(
            ReplicaDeadError(f"replica {self.instance!r} killed"),
            dead=True)
        if self._process is not None:
            try:
                self._process.terminate()
                self._process.wait(timeout=10)
            except Exception:   # noqa: BLE001 — last resort below
                try:
                    self._process.kill()
                except Exception:   # noqa: BLE001
                    pass

    def stop(self, drain=True, timeout=None):
        """Graceful stop: the replica drains (or fails queued work)
        under its own stop contract, acks, and exits; pending results
        stream back BEFORE the ack. Wire already dead -> local
        teardown only (the replica's own crash handling applies)."""
        if self._closed:
            self._reap_process(timeout or 30.0)
            return
        budget = timeout if timeout is not None else 60.0
        try:
            rid = self._mint()
            p = _PendingOp(rid, OP_STOP,
                           {"id": rid, "drain": bool(drain),
                            "timeout": timeout}, resend=False)
            self._send_op(p)
            self._await_ack(p, budget + 10.0)
            # drained results may still be in flight behind the ack
            # (the replica's sender thread is asynchronous): wait —
            # bounded — for pending streams before closing, or a
            # drain=True stop would fail futures the replica already
            # resolved
            with self._plock:
                streams = [q.stream for q in self._pending.values()
                           if q.stream is not None and not q.done]
            if streams:
                cf.wait(streams, timeout=min(10.0, budget))
        except BaseException:   # noqa: BLE001 — teardown must finish
            log.warning("replica %s stop over the wire failed; closing "
                        "locally", self.instance)
        self._shutdown_local(ServerClosedError("replica stopped"),
                             dead=False)
        self._reap_process(budget)

    def snapshot_metrics(self):
        """Refresh + return the kind snapshot (the SNAPSHOT op)."""
        return self.metrics.kind_snapshot()

    # -- internals -----------------------------------------------------
    def _fetch_snapshot(self):
        """The SNAPSHOT op: one kind snapshot off the replica (the
        `_RemoteMetrics` refresh path). TIGHT timeout: the fleet
        manager's tombstone fetches call this on the crash/drain-
        handling thread — outside the manager lock since ISSUE 15,
        but failover delivery still waits behind it — so a wedged
        wire must cost seconds, not the op default; the stale-cache
        fallback makes a miss harmless."""
        self._check_usable()
        rid = self._mint()
        p = _PendingOp(rid, OP_SNAPSHOT, {"id": rid})
        try:
            self._send_op(p)
            hdr, _ = self._await_ack(p, 5.0)
        finally:
            self._forget(rid)
        return hdr.get("snapshot") or {}

    def _check_usable(self):
        exc = self._usable_exc()
        if exc is not None:
            raise exc

    def _usable_exc(self):
        """The named error a dead/closed replica owes its callers
        (None while usable) — shared by the submit-time check and the
        raced-teardown delivery paths, so the two can never drift."""
        if self._dead:
            return ReplicaDeadError(
                f"remote replica {self.instance!r} is dead"
                + (f" ({self._dead_exc})" if self._dead_exc else ""))
        if self._closed:
            return ServerClosedError("remote replica is closed")
        return None

    def _fail_op(self, p, exc):
        """Resolve one pending op's futures with `exc` (idempotent,
        cancel-race-safe via the shared `_fail_future`): the loud-
        failure delivery every teardown path funnels through — a
        registered op must NEVER be left for its caller to time out
        on."""
        for fut in (p.ack, p.stream):
            if fut is not None:
                _fail_future(fut, exc)

    def _fail_pending(self, exc):
        with self._plock:
            pend = list(self._pending.values())
        for p in pend:
            self._fail_op(p, exc)

    def _mint(self):
        return f"{self._client_id or 'c?'}:{next(self._ids)}"

    def _forget(self, rid):
        with self._plock:
            self._pending.pop(rid, None)

    def _future_for(self, rid, exc):
        """The client future for a drained request: the one its SUBMIT
        registered, failed with the drain verdict (idempotent — the
        replica's own STREAM push may have failed it already); an
        unknown rid (a request the replica admitted locally) gets a
        fresh pre-failed future so the caller's bookkeeping stays
        uniform."""
        with self._plock:
            p = self._pending.get(rid) if rid is not None else None
        if p is not None and p.stream is not None:
            fut = p.stream
        else:
            fut = cf.Future()
        if not fut.done():
            fut.set_exception(exc)
        return fut

    def _await_ack(self, p, timeout=None):
        try:
            return p.ack.result(timeout if timeout is not None
                                else self._op_timeout)
        except cf.TimeoutError:
            raise ReplicaDeadError(
                f"wire op {p.op} to {self.instance!r} timed out after "
                f"{timeout or self._op_timeout:.0f}s") from None

    # -- connection management -----------------------------------------
    def _dial_once(self):
        """One dial attempt: connect, HELLO, start the reader, resend
        every unresolved in-flight frame (the server dedups). Each
        resent op spends one fleet retry-budget token; denied ops fail
        LOUDLY with `RetryBudgetExhaustedError` instead of riding the
        fresh socket — under a sever storm the budget bounds total
        resends fleet-wide."""
        denied = []
        try:
            self._dial_locked(denied)
        finally:
            # outside _conn_lock: failing a future runs its done
            # callbacks inline, and a fleet-manager callback may
            # re-enter submit -> lazy dial -> _conn_lock (not
            # re-entrant)
            for p in denied:
                self._forget(p.rid)
                self._count("retry_budget_exhausted")
                self._fail_op(p, RetryBudgetExhaustedError(
                    f"fleet retry budget exhausted; not resending wire "
                    f"op {p.op} ({p.rid}) to {self.instance!r}"))

    def _grant_retry(self, n=1):
        """Consult the shared fleet retry budget through the manager's
        RetryPolicy (configure_wire installed it). A policy without the
        hook — or one with no budget — always grants: the budget is an
        opt-in fleet-level brake, never a default behavior change."""
        grant = getattr(self._retry, "grant_retry", None)
        return grant is None or grant(n)

    def _dial_locked(self, denied):
        with self._conn_lock:
            if self._sock is not None:
                return
            if self._closed or self._dead:
                raise ServerClosedError("remote replica is closed")
            # graftlint: disable=lock-discipline -- the dial runs under _conn_lock BY DESIGN: the socket must not publish until HELLO + resends complete, and every contender (reconnector, lazy dials) needs the dialed socket anyway; the connect itself is bounded by connect_timeout
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._connect_timeout)
            sock.settimeout(None)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            try:
                hello = {"client_id": self._client_id}
                if self._epoch is not None:
                    hello["epoch"] = self._epoch
                # graftlint: disable=lock-discipline -- the dial-under-_conn_lock rule above: HELLO must complete before the socket publishes
                _send_frame(sock, OP_HELLO, hello)
                # graftlint: disable=lock-discipline -- the dial-under-_conn_lock rule above: HELLO must complete before the socket publishes
                op, hdr, _ = _recv_frame(sock)
                if op != OP_HELLO:
                    raise WireProtocolError(
                        f"expected HELLO reply, got op {op}")
            except BaseException:
                sock.close()
                raise
            self._client_id = hdr["client_id"]
            if self.instance is None:
                self.instance = hdr.get("instance")
                self.name = self.instance
            self._paged = bool(hdr.get("paged"))
            self._block_size = hdr.get("block_size")
            self.pid = hdr.get("pid")
            self.start_time = hdr.get("start_time")
            # resend in-flight frames BEFORE publishing the socket: a
            # failure here must leave self._sock None so the retry
            # loop re-dials — publishing first would install a broken
            # socket with NO reader to notice it (every later op would
            # stall to its timeout instead of reconnecting)
            with self._plock:
                resend = [p for p in self._pending.values()
                          if p.resend and p.sent and not p.done]
            granted = []
            for p in resend:
                (granted if self._grant_retry() else denied).append(p)
            resend = granted
            try:
                for p in resend:
                    # attempt-stamped: the server re-points delivery
                    # only for the NEWEST attempt, so a stale original
                    # frame read later off the severed connection can
                    # never steal the result back to the dead socket
                    p.attempt += 1
                    p.hdr["attempt"] = p.attempt
                    # graftlint: disable=lock-discipline -- the dial-under-_conn_lock rule above: in-flight frames must resend before the socket publishes, or a racing op could interleave ahead of them
                    _send_frame(sock, p.op, p.hdr, p.blob)
            except BaseException:
                _close_sock(sock)
                raise
            self._sock = sock
            self._gen += 1
            gen = self._gen
            if self._ever_connected:
                self._count("wire_reconnects")
            self._ever_connected = True
            if resend:
                self._count("wire_retries", len(resend))
            self._hb_last_ok = time.monotonic()
            t = threading.Thread(target=self._reader, args=(sock, gen),
                                 name="wire-reader", daemon=True)
            t.start()

    def _count(self, key, n=1):
        c = self._counters
        if c is not None:
            try:
                c.count(key, n)
            except Exception:   # noqa: BLE001 — counting never breaks IO
                pass

    def _sever_main(self):
        """The fault-injection sever callback AND internal teardown of
        a broken/desynced main connection."""
        with self._conn_lock:
            sock, self._sock = self._sock, None
        _close_sock(sock)

    def _conn_broken(self, gen, exc):
        with self._conn_lock:
            if gen != self._gen:
                return               # a newer connection took over
            sock, self._sock = self._sock, None
        _close_sock(sock)
        self._maybe_reconnect(exc)

    def _maybe_reconnect(self, cause):
        """At most one reconnector at a time; a second caller returns
        immediately — its pending op is resent by the owner (or failed
        by `_mark_dead` if the owner gives up)."""
        if not self._rc_lock.acquire(blocking=False):
            return
        try:
            attempt = 0
            while True:
                if self._closed or self._dead:
                    # ops that registered after _shutdown_local's
                    # sweep would otherwise wait out their timeouts —
                    # the teardown owes them the loud failure
                    self._fail_pending(self._usable_exc())
                    return
                with self._plock:
                    waiting = any(not p.done
                                  for p in self._pending.values())
                if not waiting and self._ever_connected:
                    # nothing in flight: dial lazily at the next op
                    return
                try:
                    # graftlint: disable=lock-discipline -- _rc_lock is the single-reconnector latch (acquired non-blocking: a contender returns instantly rather than waiting); serializing the dial IS its job
                    self._dial_once()
                    return
                except (ConnectionError, OSError) as e:
                    cause = e
                    if attempt >= self._retry.max_retries:
                        self._mark_dead(cause)
                        return
                    if not self._grant_retry():
                        # budget exhausted: stop hammering the endpoint
                        # — dead-replica delivery fails the pending ops
                        # loudly and the manager's failover path (its
                        # own budget gate) decides what survives
                        self._count("retry_budget_exhausted")
                        self._mark_dead(RetryBudgetExhaustedError(
                            f"fleet retry budget exhausted reconnecting "
                            f"to {self.instance!r} (last error: {cause})"))
                        return
                    d = self._retry.delay(attempt)
                    attempt += 1
                    log.warning(
                        "wire to %s broken (%s) — reconnect attempt %d "
                        "in %.2fs", self.instance, cause, attempt, d)
                    # graftlint: disable=lock-discipline -- the reconnect backoff sleeps inside the single-reconnector latch on purpose: contenders never block on it (non-blocking acquire), and exactly one thread may pace the retries
                    time.sleep(d)
        finally:
            self._rc_lock.release()

    def _mark_dead(self, exc):
        self._dead = True
        self._dead_exc = exc
        self._shutdown_local(ReplicaDeadError(
            f"wire to replica {self.instance!r} died: {exc}"), dead=True)

    def _shutdown_local(self, exc, dead):
        self._closed = True
        self._running = False
        self._dead = self._dead or dead
        self._hb_stop.set()
        self._sever_main()
        self._fail_pending(exc)

    def _reap_process(self, timeout):
        proc = self._process
        if proc is None:
            return
        try:
            proc.wait(timeout=timeout)
        except Exception:   # noqa: BLE001 — escalate
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:   # noqa: BLE001
                try:
                    proc.kill()
                except Exception:   # noqa: BLE001
                    pass

    # -- send / receive ------------------------------------------------
    def _send_op(self, p, site=None):
        """Register + send one op. The fault site fires AFTER the send
        — a sever there is the lost-ack scenario (module docstring).
        Any failure here just kicks the reconnector: the op is already
        registered, so the reconnect resends it and the caller's ack
        wait covers the rest."""
        with self._plock:
            self._pending[p.rid] = p
            # prune resolved entries (kept for drain's rid lookup)
            if len(self._pending) > 8192:
                for rid in [r for r, q in self._pending.items()
                            if q.done][:4096]:
                    del self._pending[rid]
        try:
            with self._conn_lock:
                if self._sock is None:
                    # lazy dial: resends skip this op (p.sent False),
                    # so the frame below is its FIRST copy — never a
                    # double-send with a spurious wire_retries
                    # graftlint: disable=lock-discipline -- the dial-under-_conn_lock rule (see _dial_once): every path that needs the socket must wait for the dial regardless
                    self._dial_once()
                sock = self._sock
            with self._wlock:
                # graftlint: disable=lock-discipline -- _wlock is the main socket's dedicated write mutex (the _Conn.send rule, client side); it never nests another lock
                _send_frame(sock, p.op, p.hdr, p.blob)
            p.sent = True
            if site is not None and self._injector is not None:
                self._injector.fire(site, on_sever=self._sever_main)
        except (FaultInjected, ConnectionError, OSError) as e:
            # the frame MAY have gone out before the failure: mark it
            # eligible for resend (dedup absorbs the may-have-arrived
            # half) and let the reconnector take it from here
            p.sent = True
            dead_exc = self._usable_exc()
            if dead_exc is not None:
                # a stop()/kill() raced past the submit-time check:
                # no reconnector is coming (it exits on closed/dead),
                # and _shutdown_local's sweep may have run BEFORE this
                # op registered — fail it loudly HERE instead of
                # stranding the caller until its op timeout
                # (graftlint future-hygiene triage, ISSUE 15)
                self._fail_op(p, dead_exc)
                return
            t = threading.Thread(target=self._maybe_reconnect, args=(e,),
                                 name="wire-reconnect", daemon=True)
            t.start()

    def _reader(self, sock, gen):
        try:
            while True:
                op, hdr, blob = _recv_frame(sock)
                if op == OP_STREAM:
                    self._on_stream(hdr)
                else:
                    self._on_reply(hdr, blob)
        except _StreamSevered as e:
            self._conn_broken(gen, e)
        except (ConnectionError, OSError) as e:
            if not (self._closed or self._dead):
                self._conn_broken(gen, e)

    def _on_stream(self, hdr):
        if self._injector is not None:
            severed = []
            self._injector.fire(
                "serve.wire.stream",
                on_sever=lambda: (self._sever_main(),
                                  severed.append(1)))
            if severed:
                # the frame died on the severed wire: the pending
                # request stays unresolved, reconnect re-SUBMITs, and
                # the server re-delivers WITHOUT re-decoding (dedup)
                raise _StreamSevered("stream severed by fault injection")
        with self._plock:
            p = self._pending.get(hdr.get("id"))
        if p is None or p.stream is None:
            return
        if not p.ack.done():
            # delivery implies acceptance — an out-of-order STREAM
            # (sender thread vs handler thread) must not strand the
            # submitter on its ack
            try:
                p.ack.set_result(({"id": p.rid, "ok": True}, b""))
            except cf.InvalidStateError:
                pass
        p.blob = b""    # registered server-side: resends dedup blob-less
        if p.stream.done():
            return
        try:
            if "error" in hdr:
                p.stream.set_exception(_exc_from_hdr(hdr))
            else:
                p.stream.set_result([int(t) for t in hdr["tokens"]])
        except cf.InvalidStateError:
            pass

    def _on_reply(self, hdr, blob):
        with self._plock:
            p = self._pending.get(hdr.get("id"))
        if p is None or p.ack.done():
            return
        try:
            if "error" in hdr:
                exc = _exc_from_hdr(hdr)
                if isinstance(exc, StaleEpochError):
                    # the fenced manager's OWN overlay shows the
                    # refusal too (the replica counted it as well —
                    # federation sums the replica side)
                    self._count("fenced_ops")
                p.ack.set_exception(exc)
                if p.stream is not None and not p.stream.done():
                    p.stream.set_exception(exc)
            else:
                p.ack.set_result((hdr, blob))
                # the request payload is no longer needed for resend:
                # the server registered the id, so a blob-less retried
                # frame dedups — dropping it here keeps a long-lived
                # client from pinning every migrated artifact's bytes
                p.blob = b""
        except cf.InvalidStateError:
            pass

    # -- heartbeats ----------------------------------------------------
    def _heartbeat_loop(self):
        """Dedicated-socket liveness (the PS client pattern): one ping
        per interval; each ack refreshes `_hb_last_ok`. Ack silence
        past `heartbeat_timeout` — severed wire, hung process, paused
        server — decays `alive` and the fleet router reaps."""
        sock = None
        while not self._hb_stop.wait(self._hb_interval):
            if self._closed or self._dead:
                break
            try:
                if sock is None:
                    sock = socket.create_connection(
                        (self._host, self._port),
                        timeout=self._connect_timeout)
                    # a bounded recv timeout: a HUNG server must read
                    # as silence, not block this thread forever
                    sock.settimeout(
                        max(self._hb_interval * 2.0,
                            min(self.heartbeat_timeout or 2.0, 2.0)))
                    hello = {"client_id": self._client_id,
                             "heartbeat": True}
                    if self._epoch is not None:
                        hello["epoch"] = self._epoch
                    _send_frame(sock, OP_HELLO, hello)
                    op, _h, _b = _recv_frame(sock)
                    if op != OP_HELLO:
                        raise WireProtocolError(
                            "bad HELLO reply on heartbeat socket")
                severed = []
                if self._injector is not None:
                    def _sever_hb():
                        severed.append(1)
                    self._injector.fire("serve.wire.heartbeat",
                                        on_sever=_sever_hb)
                if severed:
                    raise ConnectionError("heartbeat severed by fault "
                                          "injection")
                _send_frame(sock, OP_HEARTBEAT, {"id": None})
                op, _h, _b = _recv_frame(sock)
                if op != OP_HEARTBEAT:
                    raise WireProtocolError("bad HEARTBEAT reply")
                self._hb_last_ok = time.monotonic()
            except (ConnectionError, OSError):
                # best-effort: drop the socket, re-dial next tick; the
                # reap only fires after heartbeat_timeout of SILENCE
                _close_sock(sock)
                sock = None
        _close_sock(sock)


class _StreamSevered(ConnectionError):
    """Internal: a fault-injected sever consumed a STREAM frame."""
