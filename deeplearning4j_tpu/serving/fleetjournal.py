"""Durable fleet-control journal: the manager's write-ahead intent log.

`FleetManager` (serving/fleet.py) keeps the whole control plane — the
replica roster, canary state machine, autoscale history, drains in
flight — in process memory. This module makes that state durable with
the smallest possible machinery: an append-only, fsync'd,
length-prefixed + checksummed record log of fleet *intent*, one JSON
record per state transition.

Record framing (little-endian, `_HDR`)::

    u32 payload-len | u32 crc32(payload) | payload (UTF-8 JSON)

Every record is a flat JSON object with at least a ``"kind"`` field;
the rest of the fields are kind-specific (see ARCHITECTURE.md's
"Durable control plane" table). `append()` flushes and fsyncs before
returning, so a record the manager acted on is on disk before the
action's effects can be observed.

Replay follows the kvstate crash-safety discipline
(serving/kvstate.py): the *final* record may be torn — the process
died mid-write — and is dropped silently; any malformed record with
bytes after it means the file was corrupted at rest, and replay
refuses loudly with `JournalCorruptError` (a `KVStateError`) rather
than hand the manager a roster with a hole in the middle.

`fold_records()` reduces a replayed record list to the recovery
intent `FleetManager.recover()` reconciles against: current epoch,
live roster (with wire identity: host/port/pid/start_time), the
highest minted replica ordinal (so minted names stay unique across
manager generations), the shipped parameter version, and any canary
rollout that was in flight when the journal stopped.

Stdlib-only on purpose: the journal must be writable and replayable
from a process that never imports jax (tools/analyze/layers.toml pins
this module into the stdlib-only layer).
"""
from __future__ import annotations

import json
import os
import struct
import threading
import zlib

from .kvstate import KVStateError

__all__ = ["FleetJournal", "JournalBrokenError", "JournalCorruptError",
           "replay_journal", "fold_records"]

# u32 payload length | u32 crc32 of the payload bytes
_HDR = struct.Struct("<II")


class JournalCorruptError(KVStateError):
    """A journal record *before* the final one failed its length or
    checksum: the file was damaged at rest, not torn by a crash.
    Recovery must not guess at the missing history."""


class JournalBrokenError(KVStateError):
    """A previous `append()` failed mid-record AND the truncate back to
    the last good record boundary also failed: the tail of the file may
    hold torn bytes. Writing more records after them would convert a
    recoverable torn tail into mid-file corruption, so the writer
    refuses every further append."""


class FleetJournal:
    """Append-only writer. Opens in append mode so a recovered manager
    continues the same file its predecessor wrote; every `append()` is
    fsync'd before it returns. Appends are serialized under an internal
    lock and each record is written as ONE contiguous unbuffered write
    — crash/drain paths journal from done-callback and heartbeat-reap
    threads while the control thread journals spawns, and interleaving
    two records' bytes would corrupt the file mid-stream. If a write
    fails partway (e.g. ENOSPC), the file is truncated back to the last
    known-good record boundary so the tear stays at EOF where replay
    tolerates it; if even the truncate fails, the journal marks itself
    broken and refuses further appends (`JournalBrokenError`). Counts
    each durable record into the optional counters sink
    (``journal_records``) so the journal's activity shows up in the
    fleet federation."""

    def __init__(self, path, counters=None):
        self.path = str(path)
        self._counters = counters
        self._lock = threading.Lock()
        self._broken = False
        # a stale .compacting sibling is a compaction that crashed
        # BEFORE its rename commit point: the original file is intact
        # and authoritative, the half-written snapshot is garbage
        try:
            os.unlink(self.path + ".compacting")
        except OSError:
            pass
        # unbuffered: a record's single write() goes straight to the
        # fd, so there is never a buffer holding half a record that a
        # later truncate/flush could tear differently
        self._fh = open(self.path, "ab", buffering=0)
        self._fh.seek(0, os.SEEK_END)
        self._good = self._fh.tell()    # last known-good record boundary

    def size(self):
        """Bytes of known-good records on disk (the compaction
        trigger's cheap read — no stat round-trip)."""
        with self._lock:
            return self._good

    def append(self, kind, **fields):
        rec = {"kind": str(kind), **fields}
        payload = json.dumps(rec, sort_keys=True).encode("utf-8")
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._fh is None:
                raise JournalBrokenError(
                    f"fleet journal {self.path}: append after close()")
            if self._broken:
                raise JournalBrokenError(
                    f"fleet journal {self.path}: refusing append after "
                    f"an unrecovered write failure at byte {self._good}")
            try:
                mv = memoryview(frame)
                while mv:
                    mv = mv[self._fh.write(mv):]
                os.fsync(self._fh.fileno())
            except Exception:
                try:
                    os.ftruncate(self._fh.fileno(), self._good)
                except Exception:   # pragma: no cover - disk truly gone
                    self._broken = True
                raise
            self._good += len(frame)
        if self._counters is not None:
            try:
                self._counters.count("journal_records")
            except Exception:       # pragma: no cover - sink is best-effort
                pass
        return rec

    def compact(self, name_prefix="i"):
        """Fold the whole journal into ONE ``snapshot`` record and
        rotate the file atomically. The snapshot carries the complete
        fold state (epoch, roster, max_id, params_version, canary,
        quarantine, breaker), so `fold_records(replay_journal(path))`
        is IDENTICAL before and after compaction — compaction changes
        the file's size, never its meaning.

        Crash-safety is the kvstate rename-last discipline: the
        snapshot is written + fsync'd into a ``.compacting`` sibling
        first, and `os.replace` over the live path is the single
        atomic commit point. A crash before it leaves the old journal
        authoritative (the stale sibling is removed at next open); a
        crash after it leaves the compacted journal, which replays to
        the same fold. Returns the snapshot record."""
        with self._lock:
            if self._fh is None:
                raise JournalBrokenError(
                    f"fleet journal {self.path}: compact after close()")
            if self._broken:
                raise JournalBrokenError(
                    f"fleet journal {self.path}: refusing compact "
                    f"after an unrecovered write failure")
            state = fold_records(replay_journal(self.path),
                                 name_prefix=name_prefix)
            rec = {"kind": "snapshot", **state}
            payload = json.dumps(rec, sort_keys=True).encode("utf-8")
            frame = _HDR.pack(len(payload),
                              zlib.crc32(payload)) + payload
            tmp = self.path + ".compacting"
            with open(tmp, "wb") as fh:
                fh.write(frame)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)      # THE commit point
            # the old append handle points at the unlinked inode:
            # reopen on the compacted file before any further append
            self._fh.close()
            self._fh = open(self.path, "ab", buffering=0)
            self._fh.seek(0, os.SEEK_END)
            self._good = self._fh.tell()
        if self._counters is not None:
            try:
                self._counters.count("journal_records")
            except Exception:   # pragma: no cover - sink is best-effort
                pass
        return rec

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def replay_journal(path):
    """Read every intact record from `path`, in order. A missing file
    replays as an empty journal (a manager that never journaled). A
    torn final record — short header, short payload, or a checksum /
    JSON failure that extends exactly to end-of-file — is dropped
    silently: the writer died mid-append and the record never took
    effect. The same failure with bytes *after* it raises
    `JournalCorruptError`."""
    try:
        with open(str(path), "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return []
    records = []
    n = len(data)
    off = 0
    while off < n:
        if off + _HDR.size > n:
            break               # torn header at EOF: mid-append crash
        length, crc = _HDR.unpack_from(data, off)
        start = off + _HDR.size
        end = start + length
        if end > n:
            break               # torn payload at EOF: mid-append crash
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            if end == n:
                break           # final record torn mid-write
            raise JournalCorruptError(
                f"fleet journal {path}: checksum mismatch in record "
                f"{len(records)} at byte {off} with "
                f"{n - end} bytes after it")
        try:
            rec = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            if end == n:
                break           # final record torn mid-write
            raise JournalCorruptError(
                f"fleet journal {path}: undecodable record "
                f"{len(records)} at byte {off} with "
                f"{n - end} bytes after it")
        records.append(rec)
        off = end
    return records


def _ordinal(name, prefix):
    """Numeric suffix of a minted replica name (``i7`` -> 7), or None
    for names outside the mint pattern."""
    if not isinstance(name, str) or not name.startswith(prefix):
        return None
    tail = name[len(prefix):]
    return int(tail) if tail.isdigit() else None


def fold_records(records, name_prefix="i"):
    """Reduce a replayed record list to the recovery intent:

    ``epoch``
        highest manager epoch journaled (0 if never).
    ``roster``
        name -> identity dict (``host``/``port``/``pid``/
        ``start_time``/``seq``) for every replica the journal says
        should still be alive. ``spawn`` and ``adopt`` add;
        ``replica_dead`` and ``replica_drained`` remove;
        ``drain_begin`` marks the entry non-re-adoptable (a successor
        must not route to a replica its predecessor was emptying).
    ``max_id``
        highest numeric suffix ever minted under `name_prefix`, so a
        recovered manager resumes its name counter past it.
    ``params_version``
        version tag of the last parameter set rolled forward fleet
        wide (None if never swapped).
    ``canary``
        the in-flight rollout record if a ``canary_begin`` has no
        matching ``canary_rolled_forward``/``canary_rolled_back``,
        else None.
    ``quarantine``
        ordered poison-pill fingerprints (``quarantine`` records): a
        recovered manager must keep shedding a quarantined prompt, not
        resurrect it onto the fresh fleet.
    ``breaker``
        the last journaled spawn-breaker state
        (``{"state", "strikes", "backoff_s"}``) or None: a manager
        that died with the breaker OPEN must not resume the spawn
        crash-loop its predecessor escaped.

    A ``snapshot`` record (written by `FleetJournal.compact()`) seeds
    ALL of the above at once; records after it fold on top.
    """
    epoch = 0
    roster = {}
    max_id = -1
    params_version = None
    canary = None
    quarantine = []
    breaker = None
    for rec in records:
        kind = rec.get("kind")
        name = rec.get("name")
        ordinal = _ordinal(name, name_prefix)
        if ordinal is not None and ordinal > max_id:
            max_id = ordinal
        if kind == "snapshot":
            epoch = max(epoch, int(rec.get("epoch") or 0))
            roster = {k: dict(v)
                      for k, v in (rec.get("roster") or {}).items()}
            max_id = max(max_id, int(rec.get("max_id", -1)))
            params_version = rec.get("params_version")
            canary = rec.get("canary")
            quarantine = list(rec.get("quarantine") or ())
            breaker = rec.get("breaker")
        elif kind == "epoch":
            epoch = max(epoch, int(rec.get("epoch") or 0))
        elif kind in ("spawn", "adopt"):
            roster[name] = {
                "host": rec.get("host"), "port": rec.get("port"),
                "pid": rec.get("pid"),
                "start_time": rec.get("start_time"),
                "seq": rec.get("seq"), "draining": False}
        elif kind == "drain_begin":
            if name in roster:
                roster[name]["draining"] = True
        elif kind in ("replica_dead", "replica_drained"):
            roster.pop(name, None)
        elif kind == "params":
            params_version = rec.get("version")
        elif kind == "canary_begin":
            canary = dict(rec)
        elif kind in ("canary_rolled_forward", "canary_rolled_back"):
            canary = None
        elif kind == "quarantine":
            fp = rec.get("fingerprint")
            if fp and fp not in quarantine:
                quarantine.append(fp)
        elif kind == "breaker":
            breaker = {"state": rec.get("state"),
                       "strikes": rec.get("strikes"),
                       "backoff_s": rec.get("backoff_s")}
    return {"epoch": epoch, "roster": roster, "max_id": max_id,
            "params_version": params_version, "canary": canary,
            "quarantine": quarantine, "breaker": breaker}
