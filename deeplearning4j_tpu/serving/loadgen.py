"""Seeded load generation: arrival processes, size mixes, open/closed
loops.

`tools/serve_ab.py` replays fixed backlogs: every request is already
queued when the clock starts, so the servers have only ever been
measured at infinite offered load with zero queueing dynamics.
Production traffic is the opposite regime — requests ARRIVE, at some
rate, in some pattern, and the latency a user sees is mostly what the
arrival process does to the queue. This module generates that traffic:

  * Arrival processes (seeded, deterministic):
      - `PoissonProcess(rate)` — open-loop memoryless arrivals, the
        M/G/k default of load testing;
      - `OnOffProcess(rate_on, on_s, off_s)` — bursty: Poisson bursts
        separated by silence (the p99 killer — mean rate can be low
        while burst-instantaneous rate saturates the slots);
      - `ClosedLoop(concurrency)` — fixed-concurrency virtual clients,
        each submitting its next request when the previous completes.
        Included as the COORDINATED-OMISSION contrast, not the default:
        a closed loop slows its own offered load down exactly when the
        server degrades, hiding the latency it should be measuring.
  * Request-size mixes: `DecodeSizeMix` (weighted prompt/decode length
    components for `ContinuousDecodeServer`), `InferenceSizeMix`
    (feature payloads for `InferenceServer`).
  * `build_schedule(process, mix, n, seed)` -> `Schedule`: the
    DETERMINISTIC artifact. Same (process, mix, n, seed) => byte-
    identical arrival times and payloads — `digest()` is a sha256 over
    the full schedule repr, pinned by tests/test_loadgen.py — so a
    sweep point is reproducible and two arms of an A/B replay the
    identical offered stream. Seeding is string-based (process-stable),
    never `hash()` (randomized per process).
  * `run_load(server, schedule)` -> accounting dict. Open-loop
    schedules are honored by SUBMISSION TIME, never completion time: a
    slow server makes requests pile up in its queue (and shed), it does
    NOT slow the generator down. Avoiding that feedback — coordinated
    omission — is the entire point of open loop, and the no-coordination
    behavior is pinned by test against a stalling fake server.
  * `build_chaos_schedule(duration_s, n_events, seed)` ->
    `ChaosSchedule`: the FAULT-side twin of `build_schedule` — a
    deterministic, string-seeded timeline of fault actions over the
    existing injection sites (`serve.wire.*` severs, `fleet.replica`
    crash, `pause_heartbeats`) plus `manager_kill` (the durable-
    control-plane restart, guaranteed present by default so every
    seeded run exercises recovery). Same (duration, n, seed) =>
    byte-identical timeline (`digest()` pinned); the executor lives in
    `tools/load_sweep.py --chaos`.

Everything here is host-side scheduling (stdlib; numpy only lazily for
the micro-batch payload path). Driving a server adds ZERO device
dispatches beyond the requests themselves — pinned by
tests/test_loadgen.py with the PR 6 dispatch-counter A/B protocol.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import hashlib
import random
import time

from .server import ServerOverloadedError, ServingError

__all__ = ["PoissonProcess", "OnOffProcess", "ClosedLoop",
           "DecodeSizeMix", "SharedPrefixMix", "InferenceSizeMix",
           "Schedule", "ChaosSchedule", "CHAOS_ACTIONS",
           "build_schedule", "build_chaos_schedule", "run_load"]


class PoissonProcess:
    """Open-loop memoryless arrivals at `rate` requests/second."""

    kind = "poisson"
    open_loop = True

    def __init__(self, rate):
        self.rate = float(rate)
        if self.rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")

    def times(self, n, rng):
        t, out = 0.0, []
        for _ in range(n):
            t += rng.expovariate(self.rate)
            out.append(t)
        return tuple(out)


class OnOffProcess:
    """Bursty open-loop arrivals: Poisson at `rate_on` during `on_s`-long
    bursts separated by `off_s` of silence. Implemented by drawing a
    plain Poisson stream in burst-local time and mapping it onto the
    wall clock, so burst-internal statistics match `PoissonProcess`
    exactly and the mean offered rate is rate_on * on_s/(on_s+off_s)."""

    kind = "onoff"
    open_loop = True

    def __init__(self, rate_on, on_s, off_s):
        self.rate_on = float(rate_on)
        self.on_s = float(on_s)
        self.off_s = float(off_s)
        if self.rate_on <= 0 or self.on_s <= 0 or self.off_s < 0:
            raise ValueError("need rate_on > 0, on_s > 0, off_s >= 0")

    def times(self, n, rng):
        cycle = self.on_s + self.off_s
        t_on, out = 0.0, []
        for _ in range(n):
            t_on += rng.expovariate(self.rate_on)
            k = int(t_on // self.on_s)
            out.append(k * cycle + (t_on - k * self.on_s))
        return tuple(out)


class ClosedLoop:
    """Fixed-concurrency closed loop: `concurrency` virtual clients,
    each submitting its next request the moment the previous completes.
    Arrival times are an OUTPUT of the system under test (which is why
    closed loops under-report queueing latency); the schedule's
    deterministic artifact is the request sequence itself."""

    kind = "closed"
    open_loop = False

    def __init__(self, concurrency):
        self.concurrency = int(concurrency)
        if self.concurrency < 1:
            raise ValueError("concurrency must be >= 1")

    def times(self, n, rng):
        return (0.0,) * n


class DecodeSizeMix:
    """Prompt/decode length mix for the decode server: a weighted list
    of (weight, (prompt_lo, prompt_hi), (new_lo, new_hi)) components
    (hi exclusive, randrange semantics) — e.g. 'mostly short chat turns
    plus a tail of long generations', the shape that separates
    continuous from gang batching. A component may carry a FOURTH
    element, a request-class name ("interactive"/"batch"/...): its
    samples submit under that brownout class, which is how a mixed-
    class workload (the preemption A/B's shape) is generated. Classless
    components emit payloads WITHOUT a klass key, so existing
    schedules' digests are unchanged."""

    def __init__(self, components=((1.0, (3, 16), (4, 44)),), vocab=96):
        self.components = tuple(
            (float(c[0]), (int(c[1][0]), int(c[1][1])),
             (int(c[2][0]), int(c[2][1])),
             str(c[3]) if len(c) > 3 else None)
            for c in components)
        self.vocab = int(vocab)
        if not self.components:
            raise ValueError("need at least one mix component")

    def sample(self, rng):
        pick = rng.random() * sum(w for w, _, _, _ in self.components)
        for w, (plo, phi), (nlo, nhi), klass in self.components:
            pick -= w
            if pick <= 0:
                break
        prompt = tuple(rng.randrange(1, self.vocab)
                       for _ in range(rng.randrange(plo, phi)))
        out = {"prompt": prompt, "max_new": rng.randrange(nlo, nhi)}
        if klass is not None:
            out["klass"] = klass
        return out


class SharedPrefixMix:
    """Shared-system-prompt sessions: every request is one of
    `n_prefixes` SYSTEM PROMPTS followed by a per-request suffix — the
    production prompt shape where prefix caching pays (vLLM's dominant
    mix) and the one a prefix-blind fleet router destroys (N replicas
    each see every prompt ~1/N of the time, so nobody's cache stays
    warm). The system prompts are drawn ONCE, in the constructor, on an
    INDEPENDENT string-seeded stream (``loadgen.prefixes:{seed}``) —
    `build_schedule`'s size stream then only picks WHICH prompt each
    request uses plus its suffix, so the same mix object replayed under
    different schedule seeds keeps the identical prompt population.
    Prefix lengths are BLOCK-ALIGNED (`prefix_blocks` x `block_size`
    tokens): a shared prefix that ends mid-block would leave its tail
    row unsharable in the paged pool AND unhashable by the fleet
    router's block-aligned affinity key."""

    def __init__(self, n_prefixes=4, prefix_blocks=(1, 3), block_size=8,
                 suffix=(1, 9), new=(4, 16), vocab=96, seed=0,
                 klass=None):
        self.n_prefixes = int(n_prefixes)
        self.block_size = int(block_size)
        self.suffix = (int(suffix[0]), int(suffix[1]))
        self.new = (int(new[0]), int(new[1]))
        self.vocab = int(vocab)
        self.klass = str(klass) if klass is not None else None
        if self.n_prefixes < 1:
            raise ValueError("need n_prefixes >= 1")
        if self.block_size < 1:
            raise ValueError("need block_size >= 1")
        blo, bhi = int(prefix_blocks[0]), int(prefix_blocks[1])
        if blo < 1 or bhi <= blo:
            raise ValueError("prefix_blocks must be a (lo, hi) "
                             "randrange pair with lo >= 1")
        rng_p = random.Random(f"loadgen.prefixes:{seed}")
        self.prefixes = tuple(
            tuple(rng_p.randrange(1, self.vocab)
                  for _ in range(rng_p.randrange(blo, bhi)
                                 * self.block_size))
            for _ in range(self.n_prefixes))

    def sample(self, rng):
        prefix = self.prefixes[rng.randrange(self.n_prefixes)]
        tail = tuple(rng.randrange(1, self.vocab)
                     for _ in range(rng.randrange(*self.suffix)))
        out = {"prompt": prefix + tail,
               "max_new": rng.randrange(*self.new)}
        if self.klass is not None:
            out["klass"] = self.klass
        return out


class InferenceSizeMix:
    """Fixed-shape feature payloads for the micro-batch server."""

    def __init__(self, n_features):
        self.n_features = int(n_features)

    def sample(self, rng):
        return {"x": tuple(rng.gauss(0.0, 1.0)
                           for _ in range(self.n_features))}


class Schedule:
    """The deterministic offered-load artifact: arrival offsets (seconds
    relative to run start) + per-request payloads. Two schedules built
    from the same (process, mix, n, seed) are byte-identical —
    `digest()` pins it."""

    __slots__ = ("kind", "arrivals", "items", "concurrency", "meta")

    def __init__(self, kind, arrivals, items, concurrency=None,
                 meta=None):
        self.kind = kind
        self.arrivals = tuple(arrivals)
        self.items = tuple(items)
        self.concurrency = concurrency
        self.meta = dict(meta or {})
        if len(self.arrivals) != len(self.items):
            raise ValueError("arrivals and items must align")

    @property
    def n(self):
        return len(self.items)

    def offered_rps(self):
        """Offered request rate implied by the schedule (None for a
        closed loop, whose rate is an OUTPUT of the system)."""
        if self.kind == "closed" or not self.arrivals \
                or self.arrivals[-1] <= 0:
            return None
        return self.n / self.arrivals[-1]

    def offered_tokens_per_sec(self):
        toks = sum(i.get("max_new", 1) for i in self.items)
        rps = self.offered_rps()
        return None if rps is None else rps * toks / self.n

    def digest(self):
        """sha256 over the schedule's full repr: the byte-identity pin
        (payload tuples + float arrival offsets repr exactly)."""
        payload = repr((self.kind, self.concurrency, self.arrivals,
                        self.items)).encode()
        return hashlib.sha256(payload).hexdigest()


def build_schedule(process, mix, n, seed=0):
    """Materialize `n` requests from an arrival process + size mix.
    Arrival times and payloads draw from independent string-seeded
    streams so changing the mix never perturbs the arrival pattern
    (and vice versa)."""
    rng_t = random.Random(f"loadgen.arrivals:{seed}")
    rng_s = random.Random(f"loadgen.sizes:{seed}")
    arrivals = process.times(int(n), rng_t)
    items = tuple(mix.sample(rng_s) for _ in range(int(n)))
    return Schedule(process.kind, arrivals, items,
                    concurrency=getattr(process, "concurrency", None),
                    meta={"seed": seed})


# the chaos-action alphabet, each mapped to the machinery that executes
# it (tools/load_sweep.py --chaos): the four wire fault-injection sites
# (sever = the named failure scenario, see serving/wire.py's site
# table), the fleet crash site, the hung-process hook, and the durable-
# control-plane restart
CHAOS_ACTIONS = {
    "sever_submit": "serve.wire.submit",
    "sever_stream": "serve.wire.stream",
    "sever_migrate": "serve.wire.migrate",
    "sever_heartbeat": "serve.wire.heartbeat",
    "replica_crash": "fleet.replica",
    "pause_heartbeats": None,       # ReplicaServer.pause_heartbeats
    "manager_kill": None,           # kill + FleetManager.recover()
    "poison": None,                 # poison-pill request: its decode
    #                                 kills the replica it lands on
    #                                 (FleetManager kill_hook) — drives
    #                                 the quarantine verdict
    "spawn_fail": None,             # replica factory failure window —
    #                                 drives the spawn circuit breaker
}


class ChaosSchedule:
    """The deterministic fault timeline: (offset-seconds, action)
    events, time-sorted. Two schedules built from the same
    (duration_s, n_events, seed, actions) are byte-identical —
    `digest()` pins it, exactly like `Schedule.digest()` pins the
    offered load. A chaos run is therefore REPLAYABLE: the same seed
    re-fires the same faults at the same offsets."""

    __slots__ = ("events", "duration_s", "meta")

    def __init__(self, events, duration_s, meta=None):
        events = [dict(e) for e in events]
        for e in events:        # validate BEFORE the sort key reads "t"
            if "t" not in e or "action" not in e:
                raise ValueError("each chaos event needs 't' and "
                                 "'action'")
            if e["action"] not in CHAOS_ACTIONS:
                raise ValueError(
                    f"unknown chaos action {e['action']!r} (known: "
                    f"{', '.join(sorted(CHAOS_ACTIONS))})")
        self.events = tuple(sorted(events, key=lambda e: e["t"]))
        self.duration_s = float(duration_s)
        self.meta = dict(meta or {})

    @property
    def n(self):
        return len(self.events)

    def actions(self):
        return tuple(e["action"] for e in self.events)

    def digest(self):
        payload = repr(tuple(tuple(sorted(e.items()))
                             for e in self.events)).encode()
        return hashlib.sha256(payload).hexdigest()


def build_chaos_schedule(duration_s, n_events, seed=0, actions=None,
                         require_manager_kill=True, require=None):
    """Materialize a seeded chaos timeline: `n_events` actions drawn
    uniformly from `actions` (default: the full `CHAOS_ACTIONS`
    alphabet), at offsets inside the middle 80% of `duration_s` — the
    chaos must land while load is actually flowing, not before the
    first arrival or after the last. String-seeded
    (``loadgen.chaos:{seed}``) like `build_schedule`, never `hash()`.
    With `require_manager_kill` (default), a schedule that drew no
    manager kill has its middle event rewritten to one — every seeded
    run exercises journal recovery, not just wire churn. `require`
    generalizes that: a tuple of actions that must each appear at
    least once, filled in DETERMINISTICALLY (middle slot first) when
    the draw missed them — the cascade arm requires poison +
    spawn_fail + manager_kill, and the rewrite is part of the builder
    so `digest()` still pins the whole timeline from (duration_s,
    n_events, seed, actions, require) alone."""
    rng = random.Random(f"loadgen.chaos:{seed}")
    duration_s = float(duration_s)
    n = int(n_events)
    if n < 1:
        raise ValueError("need n_events >= 1")
    pool = tuple(actions if actions is not None else CHAOS_ACTIONS)
    events = [{"t": round(duration_s * (0.1 + 0.8 * rng.random()), 6),
               "action": pool[rng.randrange(len(pool))]}
              for _ in range(n)]
    if require is None:
        require = ("manager_kill",) if require_manager_kill else ()
    required = tuple(require)
    if len(required) > n:
        raise ValueError(
            f"n_events={n} cannot fit the {len(required)} required "
            f"actions {sorted(required)}")
    have = collections.Counter(e["action"] for e in events)
    slots = [n // 2] + [i for i in range(n) if i != n // 2]
    rewritten = set()
    for action in required:
        if have[action]:
            continue
        for s in slots:
            cur = events[s]["action"]
            # a slot is rewritable unless it holds the ONLY copy of
            # another required action
            if s not in rewritten and \
                    (cur not in required or have[cur] > 1):
                have[cur] -= 1
                events[s]["action"] = action
                have[action] += 1
                rewritten.add(s)
                break
        else:
            raise ValueError(
                f"n_events={n} too small to fit required action "
                f"{action!r} alongside {sorted(required)}")
    return ChaosSchedule(events, duration_s, meta={"seed": seed})


def _default_submit(server, item):
    """(future, expected generated tokens) for the two built-in payload
    kinds: 'prompt' -> ContinuousDecodeServer, 'x' -> InferenceServer."""
    if "prompt" in item:
        # klass forwarded only when the mix stamped one: classless
        # payloads keep the exact legacy call (fake/minimal servers in
        # tests need not grow a klass parameter)
        kw = {"klass": item["klass"]} if "klass" in item else {}
        return (server.submit(list(item["prompt"]), item["max_new"],
                              **kw),
                item["max_new"])
    import numpy as np      # lazy: only the micro-batch path needs arrays
    return server.submit(np.asarray(item["x"], np.float32)), 1


def run_load(server, schedule, submit=None, metrics=None,
             result_timeout=300.0):
    """Drive `server` with `schedule`; returns the accounting dict.

    Open-loop schedules submit at the SCHEDULED arrival time and never
    wait on completions mid-run (`submit_lateness_ms_max` reports how
    faithfully the generator kept to the schedule — it should stay small
    even when the server is drowning). Closed-loop schedules keep
    `schedule.concurrency` requests outstanding. Shed requests
    (`ServerOverloadedError` at submit) are counted, not raised.

    `metrics` defaults to `server.metrics`; SLO/TTFT/shed read-outs are
    DELTAS against a baseline snapshot taken at entry, so a reused
    server's earlier traffic (compile warm-up included) stays off this
    run's books.
    """
    from ..obs.registry import bucket_quantile, fmt, percentile
    from .metrics import shed_view, slo_view

    submit = submit or _default_submit
    if metrics is None:
        metrics = getattr(server, "metrics", None)
    base = metrics.snapshot() if metrics is not None else None
    # TTFT / inter-token read-outs must cover THIS run only: histogram
    # bucket counts are cumulative, so per-run quantiles come from the
    # bucket-count DELTA against entry (a reservoir couldn't do this)
    hists = (metrics.latency_histograms()
             if hasattr(metrics, "latency_histograms") else {})
    base_counts = {k: h.counts() for k, h in hists.items()}

    recs = []               # (future, expected_tokens, t_submit_abs)
    done_at = {}            # future -> completion wall time (callback)
    shed = 0
    lateness = []           # open-loop only: submit_actual - scheduled
    t0 = time.monotonic()

    def _mark_done(f):
        done_at[f] = time.monotonic()

    if schedule.kind != "closed":
        for arr, item in zip(schedule.arrivals, schedule.items):
            # honor the schedule by SUBMISSION time: sleep to the
            # scheduled offset, submit, move on — never block on a
            # result (coordinated omission is the bug, not a feature)
            while True:
                now = time.monotonic()
                if now - t0 >= arr:
                    break
                time.sleep(min(arr - (now - t0), 0.05))
            try:
                fut, toks = submit(server, item)
            except ServerOverloadedError:
                shed += 1
                continue
            t_sub = time.monotonic()
            lateness.append((t_sub - t0) - arr)
            fut.add_done_callback(_mark_done)
            recs.append((fut, toks, t_sub))
    else:
        conc = schedule.concurrency or 1
        pending, idx = set(), 0
        while idx < schedule.n or pending:
            while idx < schedule.n and len(pending) < conc:
                try:
                    fut, toks = submit(server, schedule.items[idx])
                except ServerOverloadedError:
                    shed += 1
                    idx += 1
                    continue
                t_sub = time.monotonic()
                fut.add_done_callback(_mark_done)
                pending.add(fut)
                recs.append((fut, toks, t_sub))
                idx += 1
            if pending:
                done, _ = cf.wait(pending, timeout=result_timeout,
                                  return_when=cf.FIRST_COMPLETED)
                if not done:
                    raise TimeoutError(
                        f"closed loop: no completion in "
                        f"{result_timeout}s ({len(pending)} pending)")
                pending -= done

    completed = failed = tokens_out = 0
    lat_ms = []
    deadline = time.monotonic() + result_timeout
    for fut, toks, t_sub in recs:
        try:
            fut.result(max(0.0, deadline - time.monotonic()))
        except ServingError:
            failed += 1     # shed mid-flight / deadline / closed: counted
            continue
        except Exception:   # noqa: BLE001 — accounting must finish
            failed += 1
            continue
        completed += 1
        tokens_out += toks
        # completion time came from the done callback; fall back to now
        # for a result() that raced the callback registration
        lat_ms.append((done_at.get(fut, time.monotonic()) - t_sub) * 1e3)
    t_end = max(done_at.values(), default=time.monotonic())
    duration = max(t_end - t0, 1e-9)
    lat_ms.sort()

    out = {
        "schedule": {
            "kind": schedule.kind, "n": schedule.n,
            "digest": schedule.digest(),
            "concurrency": schedule.concurrency,
            "offered_rps": fmt(schedule.offered_rps(), 3),
            "offered_tokens_per_sec": fmt(
                schedule.offered_tokens_per_sec(), 1)},
        "submitted": len(recs) + shed,
        "admitted": len(recs),
        "shed_at_submit": shed,
        "completed": completed,
        "failed": failed,
        "tokens_out": tokens_out,
        "duration_s": fmt(duration, 4),
        "requests_per_sec": fmt(completed / duration, 2),
        "tokens_per_sec": fmt(tokens_out / duration, 1),
        "latency_ms": {"p50": fmt(percentile(lat_ms, 50)),
                       "p95": fmt(percentile(lat_ms, 95)),
                       "p99": fmt(percentile(lat_ms, 99)),
                       "mean": fmt(sum(lat_ms) / len(lat_ms))
                       if lat_ms else None},
        "submit_lateness_ms_max": fmt(
            max(lateness) * 1e3 if lateness else None),
    }
    if metrics is not None:
        snap = metrics.snapshot()
        produced = snap.get("tokens_out", 0) - (base or {}).get(
            "tokens_out", 0)
        thru = (tokens_out / duration) if produced \
            else (completed / duration)
        out["slo"] = slo_view(snap, thru, base)
        for k, h in hists.items():
            delta = [c - b for c, b in zip(h.counts(), base_counts[k])]
            out[k + "_p50"] = fmt(bucket_quantile(h.buckets, delta, 50))
            out[k + "_p99"] = fmt(bucket_quantile(h.buckets, delta, 99))
            out[k + "_count"] = sum(delta)
        # shed-reason BREAKDOWN (the one shed_view implementation):
        # `shed_at_submit` above counts what THIS generator saw; the
        # per-cause deltas say why — queue backpressure vs deadline
        # expiry vs KV-block shortage vs predicted-miss admission vs
        # brownout policy — which is the difference between "the server
        # dropped work" and "overload control worked as designed"
        out["sheds"] = shed_view(snap, base)
    return out
