"""Serving metrics: request-level latency percentiles + operational gauges.

A serving SLO is a percentile, not a mean (bench.py's decode config makes
the same point for token latency) — so the core structure here is a
bounded latency reservoir per phase (queue wait, dispatch, total) with
p50/p99 read out in `snapshot()`. Everything is host-side, lock-guarded,
and O(1) per request: metrics must never add a device round-trip or a
blocking call to the serving hot path.

`snapshot()` is the ONE export surface — the same dict feeds
`ui.stats.ServingStatsReporter` (the existing UI storage path), the
`served_throughput` bench entry, and `tools/serve_ab.py`.
"""
from __future__ import annotations

import collections
import threading


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted list (no numpy: the
    metrics path must stay importable and cheap everywhere the stdlib-only
    resilience layer is)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class ServingMetrics:
    """Thread-safe counters + latency reservoirs for one serving endpoint.

    Counters: received / completed / failed / shed_deadline /
    shed_queue_full / retries / swaps / unhealthy_outputs. Gauges: queue
    depth (sampled at batch formation), batch occupancy (real requests /
    bucket slots — the padding waste measure), decode slot occupancy.
    Reservoirs keep the most recent `window` samples (deque) so a long-
    running server reports RECENT percentiles, not all-time ones.
    """

    def __init__(self, window=2048):
        self._lock = threading.Lock()
        self._window = int(window)
        self._counts = collections.Counter()
        self._lat_ms = collections.deque(maxlen=self._window)
        self._queue_wait_ms = collections.deque(maxlen=self._window)
        self._queue_depth = collections.deque(maxlen=self._window)
        self._occupancy = collections.deque(maxlen=self._window)
        self._batch_sizes = collections.deque(maxlen=self._window)
        # speculative decode reservoirs (serving/speculate.py): accepted
        # tokens per slot-dispatch and draft acceptance rate
        self._spec_accepted = collections.deque(maxlen=self._window)
        self._spec_accept_rate = collections.deque(maxlen=self._window)

    # -- hot-path recorders -------------------------------------------
    def count(self, key, n=1):
        with self._lock:
            self._counts[key] += n

    def record_request(self, total_ms, queue_wait_ms=None):
        with self._lock:
            self._counts["completed"] += 1
            self._lat_ms.append(float(total_ms))
            if queue_wait_ms is not None:
                self._queue_wait_ms.append(float(queue_wait_ms))

    def record_batch(self, n_real, bucket, queue_depth):
        with self._lock:
            self._counts["batches"] += 1
            self._batch_sizes.append(int(n_real))
            self._occupancy.append(n_real / float(bucket) if bucket else 0.0)
            self._queue_depth.append(int(queue_depth))

    def record_occupancy(self, active, slots):
        """Decode-scheduler slot occupancy for one token iteration."""
        with self._lock:
            self._occupancy.append(active / float(slots) if slots else 0.0)

    def record_speculation(self, accepted, drafted, matched):
        """One slot's share of one speculative verify dispatch: `accepted`
        tokens emitted (matched prefix + bonus), `matched` of the
        `drafted` draft tokens confirmed by the verify argmax."""
        with self._lock:
            self._counts["spec_tokens"] += int(accepted)
            self._counts["spec_drafted"] += int(drafted)
            self._counts["spec_matched"] += int(matched)
            self._spec_accepted.append(int(accepted))
            if drafted:
                self._spec_accept_rate.append(matched / float(drafted))

    # -- read-out ------------------------------------------------------
    def count_value(self, key):
        with self._lock:
            return self._counts.get(key, 0)

    def snapshot(self):
        with self._lock:
            lat = sorted(self._lat_ms)
            qw = sorted(self._queue_wait_ms)
            occ = list(self._occupancy)
            depth = list(self._queue_depth)
            sizes = list(self._batch_sizes)
            spec_acc = list(self._spec_accepted)
            spec_rate = list(self._spec_accept_rate)
            out = dict(self._counts)
        out["latency_ms_p50"] = _pct(lat, 50)
        out["latency_ms_p99"] = _pct(lat, 99)
        out["queue_wait_ms_p50"] = _pct(qw, 50)
        out["queue_wait_ms_p99"] = _pct(qw, 99)
        out["queue_depth_last"] = depth[-1] if depth else 0
        out["queue_depth_max"] = max(depth) if depth else 0
        out["batch_occupancy_mean"] = (sum(occ) / len(occ)) if occ else None
        out["batch_size_mean"] = (sum(sizes) / len(sizes)) if sizes else None
        # speculative-decode view: recent accepted-tokens-per-dispatch and
        # draft acceptance rate (reservoirs), plus the all-time dispatch
        # amortization the whole feature exists to improve
        out["spec_accepted_per_dispatch_mean"] = (
            sum(spec_acc) / len(spec_acc)) if spec_acc else None
        out["spec_acceptance_rate_mean"] = (
            sum(spec_rate) / len(spec_rate)) if spec_rate else None
        # dispatches_per_token = TARGET-model dispatches (decode/verify)
        # per emitted token — the tunnel-amortization headline for a
        # host-side draft; device_dispatches_per_token folds in the draft
        # model's own dispatches (ModelDraft pays ~K-1 per round;
        # NGramDraft pays zero) so a small-model draft cannot
        # misread as a round-trip win it does not deliver
        d, t = out.get("dispatches", 0), out.get("tokens_out", 0)
        out["dispatches_per_token"] = (d / t) if t else None
        out["device_dispatches_per_token"] = (
            (d + out.get("draft_dispatches", 0)) / t) if t else None
        return out
