"""Serving metrics: request-level latency percentiles + operational
gauges + SLO attainment counters.

A serving SLO is a percentile, not a mean (bench.py's decode config makes
the same point for token latency) — so the core structure here is a
bounded latency reservoir per phase (queue wait, dispatch, total) with
p50/p99 read out in `snapshot()`. Everything is host-side and O(1) per
request: metrics must never add a device round-trip or a blocking call
to the serving hot path.

Since PR 6 the counter/gauge/reservoir machinery lives in
`obs.registry.MetricsRegistry` — this class is a named view over a
registry (its own private one by default, or a shared/default registry
so the `/metrics` Prometheus route on ui/server.py exports serving
counters next to training-health and transport counters). The
`snapshot()` dict is unchanged and remains the ONE export surface — the
same dict feeds `ui.stats.ServingStatsReporter` (the existing UI storage
path), the `served_throughput` bench entry, and `tools/serve_ab.py`.

Queue-depth staleness fix (PR 6): depth used to be sampled ONLY at batch
formation, so an idle-then-bursty server reported the depth of the last
batch formed minutes ago. The serving loops now also record depth at
enqueue and shed time (`record_queue_depth`), so `queue_depth_last`
reflects admission pressure even before a batch forms.

TTFT + inter-token latency (PR 7): the decode server records
time-to-first-token (submit -> the slot's FIRST generated token, closed
at prefill where token 1 is produced) and an inter-token sample per
decode iteration per slot. Both are `obs.registry.Histogram`s — fixed
cumulative buckets, so they scrape as real distributions on the
Prometheus route and aggregate across endpoints, unlike the recent-
window reservoirs. These are the serving SLO metrics the fixed-backlog
A/B never needed: under ARRIVING traffic, TTFT is what queueing does to
users and inter-token is what co-residency does to streams.

SLO counters (PR 6): pass `slo_target_ms` (or have the server report
explicit per-request deadlines) and `snapshot()` carries
`slo_total` / `slo_met` / `slo_tokens_met` / `slo_attainment` — the
deadline-attainment and goodput-under-SLO numerators the ROADMAP's
production-traffic harness starts from. Shed/evicted deadline-carrying
requests count as misses: attainment is over requests ADMITTED to an
SLO, not just the ones that survived to completion.

Overload-control view (PR 9, serving/admission.py): the decode server's
service-rate estimator publishes `service_rate_tokens_per_sec` (gauge)
and the signed `admission_error_ms` histogram — (predicted - actual)
completion error per completed request, NEGATIVE when the estimator was
optimistic (the dangerous direction: optimism admits doomed requests,
pessimism sheds feasible ones) — so a wrongly-shedding estimator is
visible on the Prometheus route before it costs goodput. The shed
counters split by CAUSE (`shed_queue_full` / `shed_deadline` /
`shed_blocks` / `shed_predicted` / `shed_brownout`), rendered together
by `shed_view()` — the one breakdown implementation behind
loadgen/load_sweep/serve_ab/bench, as `slo_view` is for goodput.
"""
from __future__ import annotations

import itertools

from ..obs.registry import (MetricsRegistry, bucket_quantile, fmt,
                            percentile as _pct)

__all__ = ["ServingMetrics", "fmt", "slo_view", "shed_view"]

_ANON = itertools.count()


def slo_view(snap, throughput=None, base=None):
    """Deadline-attainment + goodput-under-SLO from one snapshot() dict:
    goodput = raw rate x fraction of output that landed within the SLO
    (tokens for decode servers, requests for batch endpoints). `base` is
    a snapshot taken AFTER any compile-off-the-clock warm-up — the
    counters are all-time, and first-compile requests are guaranteed SLO
    misses that would permanently deflate attainment. The ONE
    implementation behind tools/serve_ab.py and bench.py's serving
    records, so the attainment/goodput definition cannot drift between
    reports."""
    def delta(key):
        return snap.get(key, 0) - (base.get(key, 0) if base else 0)

    total, met = delta("slo_total"), delta("slo_met")
    out = {"slo_total": total, "slo_met": met,
           "attainment": fmt(met / total if total else None, 4)}
    produced = delta("tokens_out")
    if produced:
        frac = min(1.0, delta("slo_tokens_met") / produced)
        out["goodput_fraction"] = fmt(frac, 4)
        if throughput is not None:
            out["goodput_tokens_per_sec"] = fmt(throughput * frac, 1)
    elif total and throughput is not None:
        frac = met / total
        out["goodput_fraction"] = fmt(frac, 4)
        out["goodput_requests_per_sec"] = fmt(throughput * frac, 1)
    return out


def shed_view(snap, base=None):
    """Shed-reason breakdown from one snapshot() dict (deltas vs `base`,
    like `slo_view`): the distinct counters behind what used to print as
    one "sheds" number. ONE implementation shared by
    `serving.loadgen.run_load`, `tools/load_sweep.py`,
    `tools/serve_ab.py`, and bench.py so the column set cannot drift
    between reports. `evicted_mid_decode` rides along (it is the shed
    the admission predictor exists to prevent: work paid for, then
    thrown away)."""
    def delta(key):
        return snap.get(key, 0) - (base.get(key, 0) if base else 0)

    return {"shed_queue": delta("shed_queue_full"),
            "shed_deadline": delta("shed_deadline"),
            "shed_blocks": delta("shed_blocks"),
            "shed_predicted": delta("shed_predicted"),
            "shed_brownout": delta("shed_brownout"),
            "evicted_mid_decode": delta("evicted_mid_decode")}


class ServingMetrics:
    """Thread-safe counters + latency reservoirs for one serving endpoint.

    Counters: received / completed / failed / shed_deadline /
    shed_queue_full / retries / swaps / unhealthy_outputs + the SLO
    family. Gauges: queue depth (sampled at enqueue, shed, AND batch
    formation), batch occupancy (real requests / bucket slots — the
    padding waste measure), decode slot occupancy. Reservoirs keep the
    most recent `window` samples so a long-running server reports RECENT
    percentiles, not all-time ones.

    `registry` / `name`: where the metrics live. Default is a private
    `MetricsRegistry` (two servers never collide); pass
    `obs.default_registry()` (and a distinct `name`) to export this
    endpoint on the process-wide `/metrics` Prometheus route.
    """

    def __init__(self, window=2048, registry=None, name=None,
                 slo_target_ms=None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        if name is None:
            name = f"srv{next(_ANON)}" if registry is not None else "srv"
        self.name = name
        self._prefix = f"serving.{name}."
        self._window = int(window)
        self.slo_target_ms = (None if slo_target_ms is None
                              else float(slo_target_ms))
        res = self.registry.reservoir
        p = self._prefix
        self._lat_ms = res(p + "latency_ms", self._window)
        self._queue_wait_ms = res(p + "queue_wait_ms", self._window)
        self._queue_depth = res(p + "queue_depth", self._window)
        self._occupancy = res(p + "occupancy", self._window)
        self._batch_sizes = res(p + "batch_size", self._window)
        # speculative decode reservoirs (serving/speculate.py): accepted
        # tokens per slot-dispatch and draft acceptance rate
        self._spec_accepted = res(p + "spec_accepted", self._window)
        self._spec_accept_rate = res(p + "spec_accept_rate", self._window)
        # decode-server SLO histograms (fixed cumulative buckets — the
        # Prometheus `histogram` kind, scrapeable/aggregatable where a
        # reservoir is not); recorded by ContinuousDecodeServer
        hist = self.registry.histogram
        self._ttft_ms = hist(p + "ttft_ms")
        self._inter_token_ms = hist(p + "inter_token_ms")
        # admission-estimator observability (serving/admission.py):
        # signed (predicted - actual) completion error — the grid spans
        # NEGATIVE bounds because optimistic predictions (actual later
        # than predicted) are the dangerous direction and must not be
        # folded into the first nonnegative bucket
        self._admission_error_ms = hist(
            p + "admission_error_ms",
            buckets=(-10000, -2500, -1000, -250, -100, -25, 0,
                     25, 100, 250, 1000, 2500, 10000))
        self._service_rate = self.registry.gauge(
            p + "service_rate_tokens_per_sec")
        # paged KV-cache view (serving/kvpool.py): arena pressure as a
        # reservoir (last/max like queue depth), capacity as a gauge,
        # live decode streams as a reservoir whose MAX is the measured
        # concurrency — all on the registry, so the Prometheus route
        # exports them next to the serving counters
        self._blocks_in_use = res(p + "blocks_in_use", self._window)
        self._pool_blocks = self.registry.gauge(p + "pool_blocks")
        self._live_streams = res(p + "live_streams", self._window)
        self._counters = {}     # key -> Counter, resolved once per key
        # durable KV state (serving/kvstate.py): counters created
        # EAGERLY, not on first event — preemption/migration/restore
        # are rare by design, and a dashboard (or the Prometheus
        # route) must read zero, not absence, on a server that simply
        # has not preempted yet
        for key in ("preempted", "resumed", "migrated", "migrated_out",
                    "spill_bytes", "prefix_restore_hits"):
            self.count(key, 0)
        # fleet-control events (serving/fleet.py FleetManager): same
        # eager rule — a fleet that never failed over must scrape zero,
        # not absence, on every one of its control verbs. The wire
        # counters (serving/wire.py RemoteReplica via the manager's
        # metrics): reconnects after a severed connection, in-flight
        # frames re-sent under the at-most-once dedup, and migrations
        # a destination refused (degraded to prompt replay).
        for key in ("replica_spawned", "replica_drained", "replica_dead",
                    "replica_degraded", "failover_resubmitted",
                    "canary_rollbacks", "wire_reconnects",
                    "wire_retries", "migrate_refused"):
            self.count(key, 0)
        # durable control plane (serving/fleetjournal.py + recovery/
        # fencing in serving/fleet.py + serving/wire.py): same eager
        # rule — a fleet that never restarted its manager must scrape
        # zero, not absence, on its epoch, adoptions, fenced control
        # ops, and journal records
        for key in ("manager_epoch", "replicas_adopted", "fenced_ops",
                    "journal_records"):
            self.count(key, 0)
        # blast-radius containment (serving/fleet.py): poison-pill
        # quarantine verdicts + admission sheds, spawn-breaker opens,
        # fleet retry-budget denials, degraded-mode ticks, and
        # infant deaths — same eager rule; the breaker's live state is
        # the `breaker_state` gauge (0 closed / 0.5 half-open / 1 open)
        for key in ("requests_quarantined", "breaker_open_total",
                    "retry_budget_exhausted", "degraded_mode_ticks",
                    "infant_deaths"):
            self.count(key, 0)
        # prefix-affinity routing + the fleet prefix tier
        # (serving/fleet.py affinity policy, serving/decode.py
        # prefix_export/prefix_adopt, serving/wire.py PREFIX ops): same
        # eager rule — a fleet that never spilled or pulled must scrape
        # zero, not absence, on its routing verdicts and tier traffic
        for key in ("routed_affinity", "routed_spill",
                    "prefix_pull_hits", "prefix_pull_refused",
                    "prefix_pull_bytes"):
            self.count(key, 0)
        self._breaker_state = self.registry.gauge(p + "breaker_state")
        self._breaker_state.set(0.0)    # a fresh endpoint reads CLOSED

    @property
    def instance(self):
        """The endpoint's instance label — the identity it federates
        under (`obs.fleet.FleetView`) and exports on a labeled
        `/metrics` route. Same string as `name`; the alias exists so
        fleet code reads the intent, not the storage detail."""
        return self.name

    def kind_snapshot(self):
        """Kind-tagged state export for federation: this endpoint's
        metrics with their registry prefix stripped, each entry tagged
        counter/gauge/histogram/summary so `obs.fleet.FleetView` can
        merge N endpoints with kind-correct semantics (counters sum,
        gauges stay per-instance, histogram buckets add element-wise,
        summaries never merge). The authoritative hook — fleet code
        never reaches into the registry's private prefix scheme."""
        return self.registry.kind_snapshot(self._prefix)

    # -- hot-path recorders -------------------------------------------
    def count(self, key, n=1):
        # memoized per key: the hot path pays one dict hit + the
        # counter's own lock, never the registry lock or a string concat
        # (the module contract: O(1), lock-light per request)
        c = self._counters.get(key)
        if c is None:
            c = self._counters[key] = self.registry.counter(
                self._prefix + key)
        c.inc(n)

    def record_request(self, total_ms, queue_wait_ms=None, tokens=None,
                       deadline_met=None):
        """One completed request. `tokens` (generated tokens, or None
        for non-generative endpoints) and `deadline_met` (True/False for
        an explicit per-request deadline, None for none) feed the SLO
        counters; without an explicit deadline, `slo_target_ms` decides
        attainment from the total latency."""
        self.count("completed")
        self._lat_ms.record(float(total_ms))
        if queue_wait_ms is not None:
            self._queue_wait_ms.record(float(queue_wait_ms))
        met = deadline_met
        if met is None and self.slo_target_ms is not None:
            met = float(total_ms) <= self.slo_target_ms
        if met is not None:
            self.count("slo_total")
            if met:
                self.count("slo_met")
                if tokens:
                    self.count("slo_tokens_met", int(tokens))

    def record_slo_miss(self):
        """A deadline-carrying request that never completed (shed at
        admission or evicted mid-decode): attainment's denominator must
        include it — goodput under load is exactly about the requests
        the server gave up on."""
        self.count("slo_total")

    def record_ttft(self, ms):
        """Time-to-first-token for one request: submit -> the first
        generated token landing (the decode server closes this at
        prefill, whose argmax IS token 1)."""
        self._ttft_ms.observe(float(ms))

    def record_inter_token(self, ms):
        """One inter-token latency sample per decode iteration per slot
        (speculative iterations record delta/accepted — the per-token
        stream rate the user sees, not the per-dispatch stall)."""
        self._inter_token_ms.observe(float(ms))

    def record_admission_error(self, ms):
        """Signed (predicted - actual) completion error for one request
        the admission estimator made a prediction for: positive =
        pessimistic (finished earlier than predicted), negative =
        optimistic (the direction that admits doomed requests)."""
        self._admission_error_ms.observe(float(ms))

    def record_service_rate(self, tokens_per_sec):
        """The admission estimator's current aggregate decode rate,
        published once per scheduling iteration — the live capacity
        number predictions divide by."""
        self._service_rate.set(float(tokens_per_sec))

    def record_breaker_state(self, state):
        """The spawn circuit breaker's live state (serving/fleet.py):
        0 closed, 0.5 half-open, 1 open — a gauge, because the breaker
        is a condition, not an event stream (its event twin is
        `breaker_open_total`)."""
        self._breaker_state.set(float(state))

    def record_queue_depth(self, depth):
        """Depth sample OUTSIDE batch formation (enqueue / shed time) —
        the staleness fix: an idle-then-bursty server reports admission
        pressure, not the depth of the last batch formed minutes ago."""
        self._queue_depth.record(int(depth))

    def record_batch(self, n_real, bucket, queue_depth):
        self.count("batches")
        self._batch_sizes.record(int(n_real))
        self._occupancy.record(n_real / float(bucket) if bucket else 0.0)
        self._queue_depth.record(int(queue_depth))

    def record_occupancy(self, active, slots):
        """Decode-scheduler slot occupancy for one token iteration."""
        self._occupancy.record(active / float(slots) if slots else 0.0)

    def record_live_streams(self, n):
        """Concurrently-decoding streams this iteration; the snapshot's
        `live_streams_max` is the measured concurrency — the number the
        paged-vs-fixed A/B compares at equal arena bytes."""
        self._live_streams.record(int(n))

    def record_pool(self, in_use, capacity):
        """Paged KV arena pressure, sampled once per decode iteration:
        blocks held by live requests vs pool capacity. The event
        counters around it (`prefix_rows_hit`/`prefix_rows_total`,
        `cow_copies`, `blocked_on_memory`, `shed_blocks`) are plain
        `count()` keys recorded by the decode server at their sites."""
        self._blocks_in_use.record(int(in_use))
        self._pool_blocks.set(int(capacity))

    def record_speculation(self, accepted, drafted, matched):
        """One slot's share of one speculative verify dispatch: `accepted`
        tokens emitted (matched prefix + bonus), `matched` of the
        `drafted` draft tokens confirmed by the verify argmax."""
        self.count("spec_tokens", int(accepted))
        self.count("spec_drafted", int(drafted))
        self.count("spec_matched", int(matched))
        self._spec_accepted.record(int(accepted))
        if drafted:
            self._spec_accept_rate.record(matched / float(drafted))

    # -- read-out ------------------------------------------------------
    def latency_histograms(self):
        """The cumulative-bucket histograms by snapshot key — the PUBLIC
        handle `serving.loadgen.run_load` uses for per-run bucket-count
        deltas (reaching for the private attributes would degrade
        silently on a rename). `admission_error_ms` rides with the SLO
        pair so a sweep point reports the estimator's per-run error
        distribution next to its TTFT."""
        return {"ttft_ms": self._ttft_ms,
                "inter_token_ms": self._inter_token_ms,
                "admission_error_ms": self._admission_error_ms}

    def count_value(self, key):
        from ..obs.registry import Counter
        m = self.registry.get(self._prefix + key)
        # non-counter names (a reservoir like "latency_ms", an unset
        # gauge) report 0, matching the old Counter-dict .get(key, 0)
        return m.value if isinstance(m, Counter) else 0

    def snapshot(self):
        from ..obs.registry import Counter
        out = {}
        for n in self.registry.names(self._prefix):
            m = self.registry.get(n)
            if isinstance(m, Counter):
                out[n[len(self._prefix):]] = m.value
        lat = sorted(self._lat_ms.values())
        qw = sorted(self._queue_wait_ms.values())
        occ = self._occupancy.values()
        sizes = self._batch_sizes.values()
        spec_acc = self._spec_accepted.values()
        spec_rate = self._spec_accept_rate.values()
        out["latency_ms_p50"] = _pct(lat, 50)
        out["latency_ms_p99"] = _pct(lat, 99)
        out["queue_wait_ms_p50"] = _pct(qw, 50)
        out["queue_wait_ms_p99"] = _pct(qw, 99)
        depth_last = self._queue_depth.last()
        depth_max = self._queue_depth.max()
        out["queue_depth_last"] = 0 if depth_last is None \
            else int(depth_last)
        out["queue_depth_max"] = 0 if depth_max is None else int(depth_max)
        out["batch_occupancy_mean"] = (sum(occ) / len(occ)) if occ \
            else None
        out["batch_size_mean"] = (sum(sizes) / len(sizes)) if sizes \
            else None
        # speculative-decode view: recent accepted-tokens-per-dispatch and
        # draft acceptance rate (reservoirs), plus the all-time dispatch
        # amortization the whole feature exists to improve
        out["spec_accepted_per_dispatch_mean"] = (
            sum(spec_acc) / len(spec_acc)) if spec_acc else None
        out["spec_acceptance_rate_mean"] = (
            sum(spec_rate) / len(spec_rate)) if spec_rate else None
        # TTFT / inter-token histograms (quantiles are interpolated
        # estimates bounded by the bucket grid; None while empty). One
        # atomic state read per histogram so p50/p99/mean/count describe
        # the same instant while the serve thread keeps observing.
        for key, h in self.latency_histograms().items():
            counts, s, total = h._state()
            out[key + "_p50"] = bucket_quantile(h.buckets, counts, 50)
            out[key + "_p99"] = bucket_quantile(h.buckets, counts, 99)
            out[key + "_mean"] = (s / total) if total else None
            out[key + "_count"] = total
        # dispatches_per_token = TARGET-model dispatches (decode/verify)
        # per emitted token — the tunnel-amortization headline for a
        # host-side draft; device_dispatches_per_token folds in the draft
        # model's own dispatches (ModelDraft pays ~K-1 per round;
        # NGramDraft pays zero) so a small-model draft cannot
        # misread as a round-trip win it does not deliver
        d, t = out.get("dispatches", 0), out.get("tokens_out", 0)
        out["dispatches_per_token"] = (d / t) if t else None
        out["device_dispatches_per_token"] = (
            (d + out.get("draft_dispatches", 0)) / t) if t else None
        # fused decode windows (serving/decode.py fused_serve=K): how
        # many scheduling iterations each device dispatch amortized —
        # ~1.0 unfused, ~K fused; always-present with the window count
        # so the amortization win is a scraped number on any server
        out.setdefault("fused_windows", 0)
        out.setdefault("decode_iterations", 0)
        out["iterations_per_dispatch"] = (
            out["decode_iterations"] / d) if d else None
        # paged KV-cache pool view: always-present keys (zeros/None on a
        # fixed-slot or idle server) so dashboards and the paged A/Bs
        # read one stable surface. prefix_hit_rate is ROW-weighted —
        # the fraction of admitted prompt rows that were already
        # physically resident.
        cap = self._pool_blocks.value
        out["pool_blocks"] = 0 if cap is None else int(cap)
        in_use_last = self._blocks_in_use.last()
        in_use_max = self._blocks_in_use.max()
        out["blocks_in_use_last"] = 0 if in_use_last is None \
            else int(in_use_last)
        out["blocks_in_use_max"] = 0 if in_use_max is None \
            else int(in_use_max)
        live_max = self._live_streams.max()
        out["live_streams_max"] = 0 if live_max is None else int(live_max)
        out.setdefault("prefix_rows_hit", 0)
        out.setdefault("prefix_rows_total", 0)
        out.setdefault("cow_copies", 0)
        out.setdefault("blocked_on_memory", 0)
        out.setdefault("shed_blocks", 0)
        # overload-control view (serving/admission.py): always-present
        # keys so dashboards and the overload A/Bs read one stable
        # surface on any server, controlled or not
        out.setdefault("shed_predicted", 0)
        out.setdefault("shed_brownout", 0)
        out.setdefault("deferred", 0)
        out.setdefault("chunk_dispatches", 0)
        # prefix-hit priority admission (serving/decode.py): admits
        # that genuinely overtook queued cold-prompt work
        out.setdefault("admitted_prefix_priority", 0)
        # durable KV state (serving/kvstate.py): preempt/resume/migrate
        # event counts, host bytes spilled, and restored-prefix hits —
        # always present (eagerly created above; the setdefaults keep
        # the surface stable even for a caller-shared registry)
        out.setdefault("preempted", 0)
        out.setdefault("resumed", 0)
        out.setdefault("migrated", 0)
        out.setdefault("migrated_out", 0)
        out.setdefault("spill_bytes", 0)
        out.setdefault("prefix_restore_hits", 0)
        # fleet-control events (serving/fleet.py): spawn/drain/death,
        # failover replays, canary rollbacks — always present; plus
        # the serving-wire transport counters (serving/wire.py)
        out.setdefault("replica_spawned", 0)
        out.setdefault("replica_drained", 0)
        out.setdefault("replica_dead", 0)
        out.setdefault("replica_degraded", 0)
        out.setdefault("failover_resubmitted", 0)
        out.setdefault("canary_rollbacks", 0)
        out.setdefault("wire_reconnects", 0)
        out.setdefault("wire_retries", 0)
        out.setdefault("migrate_refused", 0)
        # durable control plane (serving/fleetjournal.py): manager
        # generation, recovery re-adoptions, fenced stale-manager ops,
        # journal records — always present
        out.setdefault("manager_epoch", 0)
        out.setdefault("replicas_adopted", 0)
        out.setdefault("fenced_ops", 0)
        out.setdefault("journal_records", 0)
        # blast-radius containment (serving/fleet.py): quarantine/
        # breaker/retry-budget/degraded-mode events — always present,
        # plus the live breaker-state gauge
        out.setdefault("requests_quarantined", 0)
        out.setdefault("breaker_open_total", 0)
        out.setdefault("retry_budget_exhausted", 0)
        out.setdefault("degraded_mode_ticks", 0)
        out.setdefault("infant_deaths", 0)
        # prefix-affinity routing + fleet prefix tier (serving/fleet.py
        # affinity policy + serving/wire.py PREFIX ops): routing
        # verdicts and cross-replica block traffic — always present
        out.setdefault("routed_affinity", 0)
        out.setdefault("routed_spill", 0)
        out.setdefault("prefix_pull_hits", 0)
        out.setdefault("prefix_pull_refused", 0)
        out.setdefault("prefix_pull_bytes", 0)
        out["breaker_state"] = self._breaker_state.value
        out["service_rate_tokens_per_sec"] = self._service_rate.value
        out["prefix_hit_rate"] = (
            out["prefix_rows_hit"] / out["prefix_rows_total"]
            if out["prefix_rows_total"] else None)
        # SLO attainment: met / (met + missed-or-shed). Always present so
        # the traffic-harness round starts from pinned keys.
        out.setdefault("slo_total", 0)
        out.setdefault("slo_met", 0)
        out.setdefault("slo_tokens_met", 0)
        out["slo_attainment"] = (out["slo_met"] / out["slo_total"]
                                 if out["slo_total"] else None)
        return out
