"""Serving layer: the request path from concurrent clients to compiled
inference programs.

The reference DL4J shipped inference as bare `output()`/`predict()` calls
on the training container; a system that "serves heavy traffic from
millions of users" (ROADMAP north star) needs the three mechanisms modern
serving systems converge on, built here over the existing containers:

  * `InferenceServer` — dynamic micro-batching with latency deadlines
    (Clipper): coalesce concurrent requests, pad to a FIXED set of bucket
    shapes so the compile cache is small and pinned, shed load explicitly.
  * `ContinuousDecodeServer` — iteration-level batching for autoregressive
    KV-cache decode (Orca): requests join/leave a fixed-slot decode
    program at token granularity, prefill separated per prompt bucket.
  * Hot model swap on both: new checkpoints route new work while in-flight
    work drains — zero dropped requests, zero recompiles.
  * Speculative decoding (`speculate.py`): a cheap draft (`NGramDraft`
    prompt-lookup or `ModelDraft` small-model) proposes K-1 tokens and
    ONE K-wide verify dispatch accepts 1..K of them — greedy streams
    pinned bit-identical to plain decode (acceptance-by-exact-argmax-
    match), so speculation is a pure dispatch-amortization lever.
  * Paged KV cache (`kvpool.py` + `ContinuousDecodeServer(paged=True)`,
    vLLM SOSP'23): fixed-size KV blocks in one arena, per-request block
    tables, free-list/refcount allocation with prompt-PREFIX reuse
    (shared leading blocks, copy-on-write before a divergent append) —
    admission gates on free blocks, so concurrency scales with memory
    actually used, not slots x worst-case length. Streams stay pinned
    bit-identical to fixed-slot and solo decode.

`ServingMetrics` (p50/p99, TTFT/inter-token histograms, queue depth,
occupancy, shed/swap counts) feeds the existing UI via
`ui.stats.ServingStatsReporter`; deadlines, backpressure, `RetryPolicy`
and `FaultInjector` sites reuse `common/resilience.py`; NaN/Inf output
screening reuses `common/health.py`.

The production-traffic harness (`loadgen.py`) drives both servers with
seeded, deterministic arrival processes (open-loop Poisson, bursty
on/off, closed-loop fixed concurrency) and request-size mixes — the
offered-load side of the ROADMAP's "handles heavy traffic" claim;
`tools/load_sweep.py` sweeps offered rate into a throughput–latency
curve with goodput-under-SLO and the saturation knee.

Durable KV state (`kvstate.py` + the zoo's `make_block_extract_fn`):
a request's KV block set leaves the arena as a tag-checked host
artifact and comes back bit-identically — preemption (`preempt=True`:
batch-class slots spill to host so blocked interactive work takes
their blocks, bounding TTFT at full block occupancy), a persistent
cross-restart prefix cache (`prefix_cache_dir=`; version-fingerprint
mismatch refuses loudly), and live-request migration between server
instances (`migrate_out`/`migrate_in`, the prefill/decode
disaggregation seam).

Overload control (`admission.py` + `ContinuousDecodeServer(
chunked_prefill=, admission=, brownout=, default_deadline_ms=)`):
chunked prefill slices long prompts into decode-iteration-sized chunks
(head-of-line surgery, streams pinned bit-identical to one-shot
prefill), a service-rate estimator sheds predicted deadline misses at
ENQUEUE (`shed_predicted`), and a per-class brownout policy makes
saturation behavior explicit — goodput stays monotone past the
saturation knee instead of collapsing.
"""
from .admission import (AdmissionController, BrownoutPolicy,
                        ServiceRateEstimator)
from .metrics import ServingMetrics
from .server import (DeadlineExceededError, InferenceServer,
                     PoisonPillError, ReplicaDeadError,
                     RequestDrainedError, RequestMigratedError,
                     ServerClosedError, ServerOverloadedError,
                     ServingError, UnhealthyOutputError)
from .decode import ContinuousDecodeServer
from .fleet import FleetManager, RoundRobinSplitter
from .fleetjournal import (FleetJournal, JournalBrokenError,
                           JournalCorruptError, fold_records,
                           replay_journal)
from .kvpool import BlockPool, PagedAllocation
from .kvstate import (KVStateError, KVStateVersionError,
                      PrefixCacheArtifact, RequestArtifact)
from .loadgen import (CHAOS_ACTIONS, ChaosSchedule, ClosedLoop,
                      DecodeSizeMix, InferenceSizeMix, OnOffProcess,
                      PoissonProcess, Schedule, SharedPrefixMix,
                      build_chaos_schedule, build_schedule, run_load)
from .speculate import DraftSource, ModelDraft, NGramDraft, Speculator
from .wire import (RemoteReplica, ReplicaServer, StaleEpochError,
                   WireProtocolError, WireRemoteError,
                   run_replica_server)

__all__ = [
    "InferenceServer", "ContinuousDecodeServer", "ServingMetrics",
    "ServingError", "ServerOverloadedError", "DeadlineExceededError",
    "UnhealthyOutputError", "ServerClosedError",
    "BlockPool", "PagedAllocation",
    "RequestArtifact", "PrefixCacheArtifact", "KVStateError",
    "KVStateVersionError", "RequestMigratedError",
    "FleetManager", "RoundRobinSplitter", "ReplicaDeadError",
    "RequestDrainedError", "PoisonPillError",
    "AdmissionController", "BrownoutPolicy", "ServiceRateEstimator",
    "Speculator", "DraftSource", "NGramDraft", "ModelDraft",
    "PoissonProcess", "OnOffProcess", "ClosedLoop",
    "DecodeSizeMix", "SharedPrefixMix", "InferenceSizeMix", "Schedule",
    "build_schedule", "run_load",
    "ChaosSchedule", "CHAOS_ACTIONS", "build_chaos_schedule",
    "ReplicaServer", "RemoteReplica", "WireProtocolError",
    "WireRemoteError", "run_replica_server", "StaleEpochError",
    "FleetJournal", "JournalBrokenError", "JournalCorruptError",
    "fold_records", "replay_journal",
]
