"""InferenceServer — adaptive micro-batching over donated jitted programs.

The request path (Clipper, NSDI'17 adaptive batching; the queueing
discipline every TPU serving stack converges on):

  submit(x) -> bounded queue -> batcher thread coalesces up to
  `max_batch` requests or `max_wait_ms`, whichever first -> the batch is
  right-padded to the nearest PADDING BUCKET -> one compiled program per
  bucket runs the dispatch -> per-request rows are sliced out and the
  futures resolved.

Design pins (tests/test_serving.py):

  * Determinism. A request's result is bit-identical no matter how it was
    batched: alone, co-batched with 7 strangers, or bucket-padded. Two
    facts make this true on a deterministic backend: (1) row results of
    the forward are independent of other rows at FIXED batch shape, and
    (2) per-row bits are identical across gemm batch shapes — measured on
    XLA:CPU for every M in {2,3,4,6,8,16}, while M=1 takes a gemv path
    with a different accumulation order. Hence the DEFAULT bucket floor is
    2: a solo request pads to [2, ...], never [1, ...]. (Pass explicit
    `buckets` containing 1 only if you do not need the cross-bucket pin.)
  * Bounded compile cache. Programs are AOT-compiled per (bucket, example
    structure) key and PINNED — a mixed-size request stream compiles at
    most len(buckets) programs per input structure, and the set never
    grows with traffic (contrast GEN_JIT_CACHE_SIZE's LRU: serving pads
    INTO the fixed set instead of evicting).
  * Hot swap. Params/model-state live in ONE reference the batcher reads
    once per dispatch; `swap()` validates the new tree's structure+shapes
    (same compiled programs stay valid — a swap is a new argument, not a
    recompile) and replaces the reference atomically. In-flight batches
    drain on the old params; queued and future requests route to the new.

Operational hardening reuses the existing subsystems: per-request
deadlines + queue backpressure shed load explicitly (`DeadlineExceeded` /
`ServerOverloaded` futures, never silent drops), transient dispatch
failures go through `common.resilience.RetryPolicy`, `FaultInjector`
sites (`serve.request`, `serve.batch`, `serve.swap`) drive the fault
tests through the real code path, and `screen_outputs=True` fails just
the NaN/Inf rows via `common.health.rowwise_finite`.
"""
from __future__ import annotations

import concurrent.futures as cf
import itertools
import logging
import queue
import threading
import time

import numpy as np

from .. import obs

log = logging.getLogger(__name__)


class ServingError(RuntimeError):
    """Base class for request-level serving failures."""


class ServerOverloadedError(ServingError):
    """Queue-full backpressure: the request was shed at admission."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired before dispatch."""


class UnhealthyOutputError(ServingError):
    """Output screening found NaN/Inf in this request's rows."""


class ServerClosedError(ServingError):
    """The server was stopped before the request could run."""


class RequestMigratedError(ServingError):
    """This request's KV state was exported to another server
    (`ContinuousDecodeServer.migrate_out`): its LOCAL future will never
    produce tokens — the importing server's future carries the resumed
    stream. Raised on the local future so a caller polling the wrong
    server fails loudly instead of hanging."""


class RequestDrainedError(ServingError):
    """This request was handed back as a REPLAY SPEC by
    `ContinuousDecodeServer.drain()` — it was queued or still
    prefilling at drain time, and a half-written prefill panel is never
    an artifact (the durable-KV victim rule, enforced at the drain
    seam). Its local future will never produce tokens; the drain caller
    (`serving.fleet.FleetManager`) resubmits the returned spec on a
    survivor, where deterministic greedy decode reproduces the exact
    stream."""


class PoisonPillError(ServingError):
    """This request was QUARANTINED by the fleet's blast-radius
    containment (serving/fleet.py): it was aboard for two or more
    distinct replica deaths, which marks it the probable killer — its
    outer future fails with this error instead of being replayed onto
    yet another survivor, and its prompt fingerprint sheds future
    re-submissions at admission. Innocent co-victims of the same
    replica deaths still fail over normally."""


class ReplicaDeadError(ServingError):
    """The replica serving (or chosen for) this request died: its serve
    loop was killed mid-stream (`ContinuousDecodeServer.kill` — the
    fleet crash-injection verb) or its thread is gone. The
    `FleetManager` resubmits in-flight requests to survivors via prompt
    replay; a direct caller sees this loudly instead of hanging on a
    future nobody will resolve."""


def _fail_future(fut, exc):
    """set_exception unless the caller already resolved/cancelled it.
    The done() pre-check alone races a concurrent cancel() — and several
    call sites run OUTSIDE a serve loop's try, where an InvalidStateError
    would kill the serve thread permanently. Returns True when the
    exception was delivered (callers count metrics only then)."""
    try:
        if not fut.done():
            fut.set_exception(exc)
            return True
    except cf.InvalidStateError:
        pass
    return False


def _resolve_future(fut, result):
    """set_result, tolerating a concurrently cancel()ed future."""
    try:
        if not fut.done():
            fut.set_result(result)
            return True
    except cf.InvalidStateError:
        pass
    return False


class _ParamsView:
    """Duck-typed (aux, blocks) holder every `swap()` accepts — the
    fleet manager's rollback snapshot / spawn carrier and the serving
    wire's SWAP deserialization target share this ONE definition."""

    __slots__ = ("aux", "blocks")

    def __init__(self, aux, blocks):
        self.aux, self.blocks = aux, blocks


class _Request:
    __slots__ = ("x", "future", "deadline", "t_submit", "req_id")

    def __init__(self, x, deadline):
        self.x = x
        self.future = cf.Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.req_id = None      # assigned at submit (the trace/request id)


def _default_buckets(max_batch):
    """Powers of two from 2 up to and including max_batch. The floor is 2
    even for max_batch=1 (a queue-only config): dispatching M=1 would take
    the gemv path whose accumulation order differs from gemm (module
    docstring), silently breaking the determinism pin."""
    out = []
    b = 2
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max(int(max_batch), 2))
    return tuple(sorted(set(out)))


class _RequestLoop:
    """Shared lifecycle for the serving loops (InferenceServer and
    ContinuousDecodeServer): bounded request queue, batcher-thread
    start/stop with drain-vs-fail-fast semantics, and the subtle
    threading guards — the submit/stop race re-check (a request enqueued
    after the loop's final drain must be failed, never silently lost),
    the join-timeout path (a loop still draining keeps `_thread` set so
    `start()` refuses a second thread), and the queued-work failure
    drain. ONE implementation so a fix here cannot drift between the two
    servers. Subclasses set `_thread_name` / `_default_stop_timeout`,
    implement `_loop_once()` (one scheduling iteration), and may
    override `_busy()` (work in progress that must finish before a
    draining stop may exit)."""

    _thread_name = "serving-loop"
    _default_stop_timeout = 30.0

    def _init_loop(self, max_queue):
        self._q = queue.Queue(maxsize=int(max_queue))
        self._running = False
        self._drain_on_stop = True
        self._thread = None
        self._req_ids = itertools.count()
        if not hasattr(self, "_tracer"):    # subclasses normally set it
            self._tracer = obs.TRACER
        if not hasattr(self, "_flight"):
            self._flight = None

    # -- hooks ---------------------------------------------------------
    def _busy(self):
        return False

    def _loop_once(self):
        raise NotImplementedError

    # -- lifecycle -----------------------------------------------------
    def start(self):
        if self._running:
            return self
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("previous serve loop has not exited yet")
        self._running = True
        self._thread = threading.Thread(target=self._serve_loop,
                                        name=self._thread_name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Stop the loop. drain=True serves everything already queued
        first; drain=False fails queued requests with ServerClosedError."""
        if not self._running:
            return
        timeout = (self._default_stop_timeout if timeout is None
                   else float(timeout))
        self._drain_on_stop = bool(drain)
        self._running = False
        t = self._thread
        t.join(timeout)
        if t.is_alive():
            # leave _thread set: start() must refuse until the loop exits
            # (and _drain_on_stop keeps the value the loop is acting on)
            log.warning("serve loop still draining after %.1fs", timeout)
            return
        self._thread = None
        self._drain_on_stop = True
        self._fail_queued(ServerClosedError("server stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- queue machinery -----------------------------------------------
    def _pending_depth(self):
        """The depth sampled at enqueue time. Subclasses with parked
        side lines (the decode server's priority line) add them here so
        every enqueue records ONE consistent number — overriding the
        sample itself would double-record."""
        return self._q.qsize()

    def _enqueue(self, req):
        """Admit `req` (has .future) or shed loudly; returns the future."""
        if req.req_id is None:
            req.req_id = next(self._req_ids)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self.metrics.count("shed_queue_full")
            # queue-depth staleness fix: a shed IS a depth observation (a
            # full queue), even when no batch forms for a while
            self.metrics.record_queue_depth(self._q.maxsize)
            raise ServerOverloadedError(
                f"queue full ({self._q.maxsize} pending)") from None
        # depth sampled at ENQUEUE, not only at batch formation: an
        # idle-then-bursty server must report admission pressure
        self.metrics.record_queue_depth(self._pending_depth())
        tr = self._tracer
        if tr.enabled:
            tr.instant("serve.enqueue", cat="serve",
                       track=f"req-{req.req_id}", trace_id=req.req_id)
        if not self._running:
            # raced stop(): the loop's final drain may already have run,
            # leaving this request in a dead queue — fail it HERE so no
            # caller ever blocks on a future nobody will resolve
            # (_fail_future: a concurrent cancel() must not turn the
            # loud shed into an InvalidStateError)
            _fail_future(req.future,
                         ServerClosedError("server stopped during "
                                           "submit"))
            raise ServerClosedError("server stopped during submit")
        return req.future

    def _fail_queued(self, exc):
        """Fail everything still queued (late submits that raced stop())."""
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if _fail_future(r.future, exc):
                self.metrics.count("failed")

    def _serve_loop(self):
        while True:
            if not self._running and not self._busy() and (
                    not self._drain_on_stop or self._q.empty()):
                break
            self._loop_once()
        self._fail_queued(ServerClosedError("server stopped"))


class InferenceServer(_RequestLoop):
    """Micro-batching inference endpoint over one network container.

    `net` is anything with `make_inference_fn()` + `_params` /
    `_model_state` (MultiLayerNetwork, ComputationGraph). Requests are
    SINGLE examples (no batch axis; dict-of-arrays for multi-input
    graphs); results are the per-example output rows as numpy.
    """

    _thread_name = "inference-server"
    _default_stop_timeout = 30.0

    def __init__(self, net, max_batch=8, max_wait_ms=2.0, buckets=None,
                 max_queue=64, default_deadline_ms=None, retry_policy=None,
                 fault_injector=None, screen_outputs=False, metrics=None,
                 stats_reporter=None, report_every=16, tracer=None,
                 flight_recorder=None):
        from .metrics import ServingMetrics
        self._tracer = tracer if tracer is not None else obs.TRACER
        self._flight = flight_recorder
        net._ensure_init()
        self._infer = net.make_inference_fn()
        self._params_ref = (net._params, net._model_state)
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait_ms) / 1e3
        self.buckets = (tuple(sorted(int(b) for b in buckets)) if buckets
                        else _default_buckets(self.max_batch))
        if self.buckets[-1] < self.max_batch:
            raise ValueError(f"largest bucket {self.buckets[-1]} < "
                             f"max_batch {self.max_batch}")
        self.default_deadline = (None if default_deadline_ms is None
                                 else float(default_deadline_ms) / 1e3)
        self._retry = retry_policy
        self._injector = fault_injector
        self._screen = bool(screen_outputs)
        self.metrics = metrics or ServingMetrics()
        self._reporter = stats_reporter
        self._report_every = max(1, int(report_every))
        self._programs = {}
        self._swap_lock = threading.Lock()
        self._since_report = 0
        self._init_loop(max_queue)

    # -- client API ----------------------------------------------------
    def submit(self, x, deadline_ms=None):
        """Enqueue one example; returns a concurrent.futures.Future whose
        result is this example's output rows. Raises ServerOverloadedError
        immediately when the queue is full (explicit backpressure — the
        caller decides whether to retry, not a hidden buffer)."""
        if not self._running:
            raise ServerClosedError("server is not running")
        if self._injector is not None:
            x = self._injector.fire("serve.request", payload=x)
        self.metrics.count("received")
        dl = (time.monotonic() + deadline_ms / 1e3 if deadline_ms is not None
              else (time.monotonic() + self.default_deadline
                    if self.default_deadline is not None else None))
        return self._enqueue(_Request(x, dl))

    def predict(self, x, deadline_ms=None, timeout=None):
        """Blocking single-request convenience wrapper over submit()."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    # -- hot swap ------------------------------------------------------
    def swap(self, new_net):
        """Install a new model's params/state without dropping in-flight
        requests: the in-flight dispatch holds its own reference and
        drains; every batch formed after this call reads the new one. The
        new tree must match the serving tree's structure and leaf shapes
        (the compiled bucket programs are reused — mismatch raises, it
        does not silently recompile into a different architecture)."""
        import jax
        with self._swap_lock:
            if self._injector is not None:
                self._injector.fire("serve.swap")
            new_net._ensure_init()
            new = (new_net._params, new_net._model_state)
            old_l, old_t = jax.tree_util.tree_flatten(self._params_ref)
            new_l, new_t = jax.tree_util.tree_flatten(new)
            if old_t != new_t:
                raise ValueError("swap rejected: param tree structure "
                                 f"differs ({new_t} vs serving {old_t})")
            for o, n in zip(old_l, new_l):
                if getattr(o, "shape", None) != getattr(n, "shape", None) \
                        or getattr(o, "dtype", None) != getattr(n, "dtype",
                                                                None):
                    raise ValueError(
                        "swap rejected: leaf mismatch "
                        f"{getattr(n, 'shape', None)}/"
                        f"{getattr(n, 'dtype', None)} vs serving "
                        f"{o.shape}/{o.dtype}")
            self._params_ref = new
            self.metrics.count("swaps")
        log.info("hot swap installed (%d swaps total)",
                 self.metrics.snapshot().get("swaps", 0))

    def swap_from_path(self, path):
        """Hot swap from a ModelSerializer zip checkpoint
        (`util/model_serializer.py`) — the architecture in the zip must
        match the serving architecture."""
        from ..util import model_serializer
        self.swap(model_serializer.restore_model(path, load_updater=False))

    def swap_from_checkpoint(self, directory, net_factory, step=None):
        """Hot swap from a ShardedCheckpointManager directory: build a
        fresh container via `net_factory()`, restore `step` (default:
        latest) into it, and swap."""
        from ..util.sharded_checkpoint import ShardedCheckpointManager
        mgr = ShardedCheckpointManager(directory)
        net = net_factory()
        net._ensure_init()
        mgr.restore(net, step if step is not None else mgr.latest_step())
        self.swap(net)

    # -- batcher internals ---------------------------------------------
    @property
    def compiled_programs(self):
        """Snapshot of the padding-bucket compile cache keys (the
        compile-cache pin counts these)."""
        return dict(self._programs)

    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _leaves(self, x):
        # SORTED key order for dicts: jax's pytree flattening sorts dict
        # keys, so two requests differing only in insertion order are the
        # same program — the cache key must agree or the compile-cache
        # pin breaks on key-order permutations
        return ([x[k] for k in sorted(x)] if isinstance(x, dict) else [x])

    def _struct_key(self, x):
        """Structure signature of one example: the batching/compile-cache
        unit (dict key set + per-leaf shape/dtype, key-order-insensitive)."""
        if isinstance(x, dict):
            names = tuple(sorted(x))
        else:
            names = None
        return (names, tuple((tuple(np.shape(l)), str(np.asarray(l).dtype))
                             for l in self._leaves(x)))

    def _program(self, bucket, example):
        import jax
        key = (bucket, self._struct_key(example))
        prog = self._programs.get(key)
        if prog is None:
            params, state = self._params_ref
            if isinstance(example, dict):
                xs = {k: jax.ShapeDtypeStruct(
                    (bucket,) + tuple(np.shape(v)),
                    np.asarray(v).dtype) for k, v in example.items()}
            else:
                xs = jax.ShapeDtypeStruct(
                    (bucket,) + tuple(np.shape(example)),
                    np.asarray(example).dtype)
            # AOT per bucket: lower+compile ONCE, pinned forever. The
            # request tensor is NOT donated — its shape can never alias
            # the output's, so XLA could not reuse the buffer anyway
            # (the decode path donates its KV cache, where aliasing is
            # total); params stay undonated because every batch reuses
            # them.
            prog = jax.jit(self._infer).lower(params, state, xs).compile()
            self._programs[key] = prog
            log.info("compiled serving program bucket=%d (%d cached)",
                     bucket, len(self._programs))
        return prog

    def _stack_pad(self, reqs, bucket):
        """[n_real examples] -> bucket-padded batch (zero rows pad; row
        independence makes pad content irrelevant to real rows)."""
        def stack(*rows):
            a = np.stack([np.asarray(r) for r in rows])
            if a.shape[0] < bucket:
                pad = np.zeros((bucket - a.shape[0],) + a.shape[1:],
                               a.dtype)
                a = np.concatenate([a, pad])
            return a
        first = reqs[0].x
        if isinstance(first, dict):
            return {k: stack(*[r.x[k] for r in reqs]) for k in first}
        return stack(*[r.x for r in reqs])

    def _collect(self):
        """Coalesce one micro-batch: block for the first request, then
        fill until max_batch or max_wait — capped by the earliest deadline
        so a tight-deadline request is not queued past its budget."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        t_close = time.monotonic() + self.max_wait
        while len(batch) < self.max_batch:
            now = time.monotonic()
            close = t_close
            for r in batch:
                if r.deadline is not None:
                    close = min(close, r.deadline)
            if now >= close:
                break
            try:
                batch.append(self._q.get(timeout=close - now))
            except queue.Empty:
                break
        return batch

    def _loop_once(self):
        batch = self._collect()
        if not batch:
            return
        try:
            self._run_batch(batch)
        except BaseException as e:  # noqa: BLE001 — fail futures
            n_failed = 0
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                    n_failed += 1
            if n_failed:
                self.metrics.count("failed", n_failed)

    def _run_batch(self, batch):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.future.done():       # failed by a raced submit/stop
                continue
            if r.deadline is not None and now > r.deadline:
                r.future.set_exception(DeadlineExceededError(
                    f"deadline missed by {(now - r.deadline) * 1e3:.1f}ms "
                    "before dispatch"))
                self.metrics.count("shed_deadline")
                self.metrics.record_slo_miss()
            else:
                live.append(r)
        if not live:
            return
        # heterogeneous traffic: requests with different input structures
        # cannot share a dispatch — partition by the SAME key the compile
        # cache uses, so one odd-shaped request can never fail its
        # co-batched neighbours
        groups = {}
        for r in live:
            groups.setdefault(self._struct_key(r.x), []).append(r)
        for group in groups.values():
            self._dispatch_group(group, now)
        # cadence by batches-SINCE-LAST-REPORT, not a modulo on the shared
        # counter: multi-group dispatches advance the counter by >1 and
        # would make a modulo land arbitrarily rarely
        self._since_report += len(groups)
        if self._reporter is not None and \
                self._since_report >= self._report_every:
            self._since_report = 0
            self._reporter.report(self.metrics.snapshot())

    def _dispatch_group(self, live, now):
        tr = self._tracer
        bucket = self._bucket_for(len(live))
        self.metrics.record_batch(len(live), bucket, self._q.qsize())
        if tr.enabled:
            # close each request's queue-wait span now that its batch
            # exists (t_submit shares monotonic_ns's clock base)
            now_ns = time.monotonic_ns()
            for r in live:
                t0 = int(r.t_submit * 1e9)
                tr.emit("serve.queue_wait", t0, now_ns - t0, cat="serve",
                        track=f"req-{r.req_id}", trace_id=r.req_id)
        with tr.span("serve.batch", cat="serve", track="server",
                     bucket=bucket, n_real=len(live)):
            prog = self._program(bucket, live[0].x)
            params, state = self._params_ref     # ONE read: swap-atomic
            x = self._stack_pad(live, bucket)

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                return prog(params, state, x)

            with tr.span("serve.dispatch", cat="serve", track="server",
                         bucket=bucket):
                if self._retry is not None:
                    out = self._retry.call(
                        dispatch,
                        on_retry=lambda a, e, d: self.metrics.count(
                            "retries"))
                else:
                    out = dispatch()
            rows = [np.asarray(l) for l in
                    (out if isinstance(out, (list, tuple)) else [out])]
            single = not isinstance(out, (list, tuple))
            ok = None
            if self._screen:
                from ..common.health import rowwise_finite
                ok = rowwise_finite(rows)
            t_done = time.monotonic()
            for i, r in enumerate(live):
                if r.future.done():
                    continue
                if ok is not None and not ok[i]:
                    r.future.set_exception(UnhealthyOutputError(
                        "non-finite values in request output"))
                    self.metrics.count("unhealthy_outputs")
                    continue
                res = [a[i] for a in rows]
                r.future.set_result(res[0] if single else res)
                total_ms = (t_done - r.t_submit) * 1e3
                self.metrics.record_request(
                    total_ms, (now - r.t_submit) * 1e3,
                    deadline_met=(None if r.deadline is None
                                  else t_done <= r.deadline))
                if tr.enabled:
                    t0 = int(r.t_submit * 1e9)
                    tr.emit("serve.request", t0,
                            int((t_done - r.t_submit) * 1e9), cat="serve",
                            track=f"req-{r.req_id}", trace_id=r.req_id)
                if self._flight is not None:
                    self._flight.observe(total_ms)
