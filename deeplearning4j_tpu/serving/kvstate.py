"""Durable, migratable KV state: one serialization primitive for a
request's KV block set, three production scenarios.

A slot's KV state — its blocks in the arena, its position, its block
table — has always been trapped in the server process: blocks die with
the arena, a preempted request loses every row it paid for, and a
request can never move between server instances. This module makes that
state a first-class HOST-SIDE ARTIFACT:

  * `RequestArtifact` — one live request's KV panel (all real rows
    `[0, pos)`, gathered out of the arena by the zoo's
    `make_block_extract_fn`), its token history (prompt + generated),
    its position, and the PARAM VERSION TAG the rows were computed
    under. Restoring it into any paged decode server running the same
    params resumes the stream bit-identically: the panel rows are the
    same bits prefill/decode would recompute (per-row bits are
    independent of batch shape — the measured property every serving
    pin rests on), so installing them is indistinguishable from having
    computed them.
  * `PrefixCacheArtifact` — the LRU prefix cache's resident blocks
    (token-prefix keys + row panels) under one version tag, saved at
    `stop()` and re-offered by a restarted server: warm system prompts
    survive a crash or a deploy.

Three consumers in `ContinuousDecodeServer` (decode.py):
PREEMPTION (spill a batch-class slot to host, give its blocks to an
interactive request, resume later bit-identically), the persistent
prefix cache above, and MIGRATION (export a live request from one
server, import into another — the seam prefill/decode disaggregation
and replica fleets consume).

Like `kvpool` and `admission`, this module is jax-free (numpy only, for
the host panels the decode server already holds): serialization can
never add a device dispatch, and everything unit-tests without a
device. The on-disk format follows the `ShardedCheckpointManager`
protocol conventions (util/sharded_checkpoint.py) without its orbax
dependency — KV panels are plain host arrays, not sharded jax trees:
one directory per artifact, a raw little-endian `panels.bin` plus a
`manifest.json` describing every array (dtype/shape/offset), committed
CRASH-SAFELY in the manager's ordering (the new artifact is fully
staged — payload first, manifest `os.replace`d last — before the old
one is swapped out, and a loader treats a manifest-less directory as
absent: a crash mid-save leaves the predecessor readable or a clean
cold start, never a destroyed-old-with-no-new and never a
half-readable mix).

VERSION SAFETY is the load-bearing rule: an artifact's rows are only
valid under the exact params that computed them. Every artifact carries
`tag` — the decode server stamps a content fingerprint of its param
version — and every restore path calls `require_tag()` first, which
raises `KVStateVersionError` on mismatch: a prefix cache saved under
params v1 restored into a server running v2 refuses the blocks loudly
(zero silent reuse — the in-process hot-swap invalidation rule,
extended across restarts), and a migration between servers running
different params refuses the request the same way.
"""
from __future__ import annotations

import json
import os
import shutil
import struct

import numpy as np

__all__ = ["RequestArtifact", "PrefixCacheArtifact", "KVStateError",
           "KVStateVersionError", "FORMAT_VERSION", "artifact_kind",
           "artifact_from_bytes"]

# bumped on any incompatible layout change; loaders refuse unknown
# versions loudly instead of misreading bytes
FORMAT_VERSION = 1

_MANIFEST = "manifest.json"
_PANELS = "panels.bin"


class KVStateError(RuntimeError):
    """Base class for durable-KV-state failures (corrupt/missing
    artifact, shape mismatch against the target server)."""


class KVStateVersionError(KVStateError):
    """The artifact's param version tag does not match the target
    server's — its rows were computed under different weights and MUST
    NOT be reused (the cross-restart twin of the in-process hot-swap
    invalidation rule)."""


def artifact_kind(path):
    """'request' / 'prefix_cache' for a committed artifact directory,
    None for anything else (absent, mid-crash payload without its
    manifest, unreadable) — the warm-start probe the decode server runs
    at construction, which must treat every non-artifact as a cold
    start, never an error."""
    mpath = os.path.join(os.path.abspath(path), _MANIFEST)
    try:
        with open(mpath) as fh:
            return json.load(fh).get("kind")
    except (OSError, ValueError):
        return None


def _panels_nbytes(panels):
    return sum(int(a.nbytes) for kv in panels for a in kv)


def _check_panels(panels):
    """Normalize one panel set: per layer a (k, v) pair of equal-shape
    [rows, H, hd] float arrays, UNIFORM across layers — a later layer
    with fewer rows (corrupt payload, skewed foreign producer) must
    refuse loudly here, not zero-fill silently at install time."""
    out = []
    for kv in panels:
        k, v = kv
        k = np.asarray(k)
        v = np.asarray(v)
        if k.shape != v.shape or k.dtype != v.dtype or k.ndim != 3:
            raise KVStateError(
                f"malformed KV panel: k {k.shape}/{k.dtype} vs "
                f"v {v.shape}/{v.dtype} (need matching [rows, H, hd])")
        if out and (k.shape != out[0][0].shape
                    or k.dtype != out[0][0].dtype):
            raise KVStateError(
                f"malformed KV panel: layer {len(out)} is "
                f"{k.shape}/{k.dtype} but layer 0 is "
                f"{out[0][0].shape}/{out[0][0].dtype} (layers must be "
                f"uniform)")
        out.append((k, v))
    if not out:
        raise KVStateError("artifact needs at least one layer panel")
    return out


def _serialize_arrays(arrays):
    """ONE layout for every serialization target: flatten `arrays`
    into (descriptors, chunk generator) — descriptors carry dtype/
    shape/offset/nbytes into the concatenation of the yielded chunks.
    `to_bytes()` joins the chunks into one wire buffer; the disk path
    writes them SEQUENTIALLY, holding one array's bytes at a time (a
    multi-GB prefix-cache save must never transiently double its
    footprint) — same bytes either way, so the wire and disk
    serializers structurally cannot drift."""
    norm = [np.ascontiguousarray(a) for a in arrays]
    descs, off = [], 0
    for a in norm:
        descs.append({"dtype": str(a.dtype),
                      "shape": list(a.shape),
                      "offset": off,
                      "nbytes": int(a.nbytes)})
        off += int(a.nbytes)
    return descs, (a.tobytes() for a in norm)


def _deserialize_arrays(manifest, raw):
    """The shared inverse: descriptors + payload bytes -> read-only
    array views over `raw` (the buffer stays alive through each
    array's base). A payload shorter than its descriptors promise —
    a truncated wire buffer or half-written panels.bin — refuses as
    KVStateError like every other corruption mode, never a bare
    numpy ValueError (which a wire consumer would misclassify as a
    request-level verdict)."""
    arrays = []
    try:
        for d in manifest["arrays"]:
            a = np.frombuffer(raw, dtype=np.dtype(d["dtype"]),
                              count=int(np.prod(d["shape"],
                                                dtype=np.int64))
                              if d["shape"] else 1,
                              offset=d["offset"]).reshape(d["shape"])
            arrays.append(a)
    except (ValueError, TypeError) as e:
        raise KVStateError(f"corrupt artifact payload: {e}") from e
    return arrays


def _check_manifest(manifest, kind, where):
    fv = manifest.get("format_version")
    if fv != FORMAT_VERSION:
        raise KVStateError(
            f"{kind} artifact {where} has format_version {fv!r}; "
            f"this build reads {FORMAT_VERSION}")
    if kind is not None and manifest.get("kind") != kind:
        raise KVStateError(
            f"artifact {where} is a {manifest.get('kind')!r}, "
            f"expected {kind!r}")


def _write_payload(path, manifest, arrays):
    """Commit `arrays` + `manifest` under directory `path` with the
    checkpoint-manager crash ordering: the NEW artifact is fully
    written into a sibling staging directory (payload bytes first, the
    manifest last via atomic os.replace) BEFORE the previous committed
    artifact is touched, then the directories swap. A crash at any
    point leaves either the old artifact readable or (in the rename
    window) no artifact at `path` — a cold start — never a destroyed
    predecessor with no successor and never a half-readable mix (a
    manifest-less directory reads as absent; fsync is not issued, so
    power loss can still cost the newest save). An existing artifact
    at `path` is replaced (the fixed-path periodic-save pattern)."""
    path = os.path.abspath(path)
    stage = path + ".staging"
    trash = path + ".stale"
    for d in (stage, trash):        # leftovers from a crashed save
        if os.path.isdir(d):
            shutil.rmtree(d)
    os.makedirs(stage)
    descs, chunks = _serialize_arrays(arrays)
    with open(os.path.join(stage, _PANELS), "wb") as fh:
        for chunk in chunks:        # one array's bytes at a time
            fh.write(chunk)
    manifest = dict(manifest)
    manifest["format_version"] = FORMAT_VERSION
    manifest["arrays"] = descs
    tmp = os.path.join(stage, _MANIFEST + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
    os.replace(tmp, os.path.join(stage, _MANIFEST))  # atomic on POSIX
    if os.path.isdir(path):
        os.rename(path, trash)      # old artifact parked, not deleted
    os.rename(stage, path)          # the commit point
    shutil.rmtree(trash, ignore_errors=True)
    return path


def _read_payload(path, kind):
    path = os.path.abspath(path)
    mpath = os.path.join(path, _MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"no {kind} artifact at {path!r} (missing {_MANIFEST})")
    with open(mpath) as fh:
        manifest = json.load(fh)
    _check_manifest(manifest, kind, f"at {path!r}")
    with open(os.path.join(path, _PANELS), "rb") as fh:
        raw = fh.read()
    return manifest, _deserialize_arrays(manifest, raw)


def _pack_bytes(manifest, arrays):
    """The wire layout: `u32 manifest_len | manifest_json | payload` —
    the manifest+panels directory layout as ONE buffer (no temp dir).
    Shares `_serialize_arrays` with the disk path byte-for-byte."""
    descs, chunks = _serialize_arrays(arrays)
    manifest = dict(manifest)
    manifest["format_version"] = FORMAT_VERSION
    manifest["arrays"] = descs
    hdr = json.dumps(manifest).encode()
    return b"".join([struct.pack("<I", len(hdr)), hdr, *chunks])


def _parse_buffer(buf):
    """Guarded header parse of a `to_bytes()` buffer: every corruption
    mode (truncation, overrun, bad JSON) surfaces as the KVStateError
    family — the ONE parse behind `_unpack_bytes` and
    `artifact_from_bytes`, so their error classification cannot
    drift."""
    buf = bytes(buf) if not isinstance(buf, (bytes, bytearray)) else buf
    if len(buf) < 4:
        raise KVStateError("truncated artifact buffer (no header)")
    (hlen,) = struct.unpack_from("<I", buf, 0)
    if 4 + hlen > len(buf):
        raise KVStateError("truncated artifact buffer (header cut off)")
    try:
        manifest = json.loads(buf[4:4 + hlen].decode())
    except ValueError as e:
        raise KVStateError(f"corrupt artifact manifest: {e}") from e
    return manifest, memoryview(buf)[4 + hlen:]


def _unpack_bytes(buf, kind):
    manifest, payload = _parse_buffer(buf)
    _check_manifest(manifest, kind, "in wire buffer")
    return manifest, _deserialize_arrays(manifest, payload)


def artifact_from_bytes(buf):
    """Deserialize either artifact kind from a `to_bytes()` buffer —
    the wire consumer's one-call probe (the serving wire's MIGRATE
    payloads carry request artifacts; a foreign producer may ship a
    prefix cache through the same frames). ONE manifest parse through
    the same guarded pipeline `from_bytes` uses."""
    manifest, flat = _unpack_bytes(buf, None)   # kind checked below
    kind = manifest.get("kind")
    cls = {"request": RequestArtifact,
           "prefix_cache": PrefixCacheArtifact}.get(kind)
    if cls is None:
        raise KVStateError(f"unknown artifact kind {kind!r} in buffer")
    return cls._from_manifest(manifest, flat)


def _pair_up(flat):
    """Reassemble the flat array list back into per-layer (k, v)."""
    return [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]


class _TaggedArtifact:
    """Shared version-tag contract for both artifact kinds."""

    tag = None

    def require_tag(self, expected, what="artifact"):
        """Fail LOUDLY unless this artifact was produced under param
        version `expected` — the one rule that makes durable KV state
        safe at all (module docstring)."""
        if self.tag != expected:
            raise KVStateVersionError(
                f"{what} was saved under param version tag "
                f"{self.tag!r} but the server is running "
                f"{expected!r}: its KV rows were computed under "
                f"different weights and cannot be reused (re-run the "
                f"request / warm the cache cold instead)")


class RequestArtifact(_TaggedArtifact):
    """One request's complete resumable KV state.

    panels:    per layer (k, v), each [pos, H, hd] — every REAL row the
               request has written (prompt + generated-but-last;
               extraction slices the table gather at the frontier).
    prompt:    the prompt tokens (restore re-runs the prefix match on
               them — shared leading blocks are RE-ACQUIRED through the
               prefix index, never duplicated).
    generated: tokens emitted so far (the last one is the next decode
               input; the resumed stream appends after it).
    max_new:   the request's original token budget.
    tag:       param-version fingerprint the rows were computed under.
    block_size: the source pool's block size (restore validates it —
               panel rows are layout-independent, but the logical
               position math the artifact froze is not).
    klass:     brownout request class, carried so a migrated/resumed
               request keeps its policy treatment.
    trace:     optional TRACE CONTEXT dict ({"trace_id", "parent_span",
               "origin"} — obs.trace.TraceContext.to_manifest()): the
               Dapper baton. A destination server continues the
               request's `req-<id>` lane under the SAME trace id, so
               the two instances' saved traces stitch into one
               timeline (obs.fleet.merge_traces). Pure metadata: never
               consulted by any restore-correctness path, absent in
               pre-trace artifacts, and a foreign producer may omit it.
    """

    __slots__ = ("prompt", "generated", "max_new", "tag", "block_size",
                 "klass", "panels", "trace")

    def __init__(self, prompt, generated, max_new, tag, block_size,
                 panels, klass="default", trace=None):
        self.prompt = tuple(int(t) for t in prompt)
        self.generated = tuple(int(t) for t in generated)
        if not self.prompt or not self.generated:
            raise KVStateError("a request artifact needs a prompt and "
                               "at least one generated token (requests "
                               "are only extractable in decode phase)")
        self.max_new = int(max_new)
        self.tag = str(tag)
        self.block_size = int(block_size)
        self.klass = str(klass)
        # accept a mapping or anything with to_manifest() (TraceContext)
        if trace is not None and hasattr(trace, "to_manifest"):
            trace = trace.to_manifest()
        self.trace = dict(trace) if trace else None
        self.panels = _check_panels(panels)
        if self.panels[0][0].shape[0] != self.pos:
            raise KVStateError(
                f"panel rows {self.panels[0][0].shape[0]} != frontier "
                f"position {self.pos} (prompt + generated - 1)")

    @property
    def pos(self):
        """The frontier: rows written so far. The final generated token
        has not been written back (the decode loop's contract: the last
        emitted token needs no cache row until the next step writes
        it)."""
        return len(self.prompt) + len(self.generated) - 1

    @property
    def remaining(self):
        return self.max_new - len(self.generated)

    @property
    def nbytes(self):
        """Host bytes this artifact's KV panel occupies — the
        `spill_bytes` accounting unit."""
        return _panels_nbytes(self.panels)

    def _manifest_and_flat(self):
        """ONE manifest builder behind save() and to_bytes() — the two
        serializers share every field and the panel flattening, so the
        wire and disk layouts cannot drift."""
        flat = [a for kv in self.panels for a in kv]
        manifest = {
            "kind": "request",
            "tag": self.tag,
            "prompt": list(self.prompt),
            "generated": list(self.generated),
            "max_new": self.max_new,
            "block_size": self.block_size,
            "klass": self.klass,
            "n_layers": len(self.panels),
        }
        if self.trace is not None:
            manifest["trace"] = self.trace
        return manifest, flat

    @classmethod
    def _from_manifest(cls, m, flat):
        return cls(m["prompt"], m["generated"], m["max_new"], m["tag"],
                   m["block_size"], _pair_up(flat), klass=m["klass"],
                   trace=m.get("trace"))

    def save(self, path):
        return _write_payload(path, *self._manifest_and_flat())

    @classmethod
    def load(cls, path):
        return cls._from_manifest(*_read_payload(path, "request"))

    def to_bytes(self):
        """The whole artifact as ONE buffer (`u32 manifest_len |
        manifest_json | panel payload`) — the serving wire's MIGRATE
        payload. Byte-identical panel layout to `save()`'s panels.bin
        (shared `_serialize_arrays`), no temp dir."""
        return _pack_bytes(*self._manifest_and_flat())

    @classmethod
    def from_bytes(cls, buf):
        return cls._from_manifest(*_unpack_bytes(buf, "request"))


class PrefixCacheArtifact(_TaggedArtifact):
    """The prefix cache's resident blocks under ONE version tag.

    entries: list of (prefix_tokens tuple, per-layer (k, v) panels each
    [block_size, H, hd]) — exactly the `BlockPool` index's (key ->
    block) mapping with the physical rows pulled to host. Entries are
    kept PARENT-FIRST (sorted by prefix length) so a restore adopts a
    chain in matchable order; a child whose parent was LRU-evicted
    before the save simply restores unmatchable, which is harmless
    (match_prefix walks full prefixes from the front)."""

    __slots__ = ("tag", "block_size", "entries")

    def __init__(self, tag, block_size, entries):
        self.tag = str(tag)
        self.block_size = int(block_size)
        norm = []
        for prefix, panels in entries:
            prefix = tuple(int(t) for t in prefix)
            panels = _check_panels(panels)
            if panels[0][0].shape[0] != self.block_size:
                raise KVStateError(
                    f"prefix-cache panel carries "
                    f"{panels[0][0].shape[0]} rows; every entry is "
                    f"exactly one {self.block_size}-row block")
            if len(prefix) % self.block_size:
                raise KVStateError(
                    f"prefix key length {len(prefix)} is not a "
                    f"multiple of block_size {self.block_size}")
            norm.append((prefix, panels))
        self.entries = sorted(norm, key=lambda e: len(e[0]))

    @property
    def nbytes(self):
        return sum(_panels_nbytes(p) for _, p in self.entries)

    def _manifest_and_flat(self):
        flat = [a for _, panels in self.entries
                for kv in panels for a in kv]
        return {
            "kind": "prefix_cache",
            "tag": self.tag,
            "block_size": self.block_size,
            "prefixes": [list(p) for p, _ in self.entries],
            "n_layers": (len(self.entries[0][1])
                         if self.entries else 0),
        }, flat

    @classmethod
    def _from_manifest(cls, m, flat):
        n_layers = int(m["n_layers"])
        per_entry = 2 * n_layers
        entries = []
        for i, prefix in enumerate(m["prefixes"]):
            chunk = flat[i * per_entry:(i + 1) * per_entry]
            entries.append((prefix, _pair_up(chunk)))
        return cls(m["tag"], m["block_size"], entries)

    def save(self, path):
        return _write_payload(path, *self._manifest_and_flat())

    @classmethod
    def load(cls, path):
        return cls._from_manifest(*_read_payload(path, "prefix_cache"))

    def to_bytes(self):
        """One-buffer serialization (see RequestArtifact.to_bytes) —
        a restarted remote replica could warm its prefix cache straight
        off a peer instead of disk."""
        return _pack_bytes(*self._manifest_and_flat())

    @classmethod
    def from_bytes(cls, buf):
        return cls._from_manifest(*_unpack_bytes(buf, "prefix_cache"))
