"""Overload control: deadline-aware admission + brownout policy.

PR 7's load_sweep measured what uncontrolled overload does to the decode
server: past the saturation knee, goodput-under-SLO COLLAPSES (2,515 ->
635 tok/s on the pinned CPU curve) because every admitted request eats
queue time until its deadline is unmakeable, then either dies mid-decode
(wasting the tokens it already got) or completes uselessly late —
queue_wait was 72% of all request time. The fix is classical overload
control: decide at ENQUEUE, against a live estimate of service capacity,
whether a request can possibly make its deadline — and if it cannot,
shed it IMMEDIATELY, before it costs anyone anything.

Three host-side pieces (stdlib-only, like kvpool: admission decisions
can never add a device dispatch, and everything unit-tests without a
device):

* **ServiceRateEstimator** — over the decode loop's recent scheduling
  iterations: a rolling MEDIAN of SECONDS PER ITERATION (wall time,
  chunk-prefill passes folded in) and an EWMA of TOKENS PER ACTIVE
  SLOT per token-bearing iteration (exactly 1.0 in plain decode; >1
  under speculation). Iteration time is the right primitive because it
  is OCCUPANCY-INDEPENDENT — the slot program computes every slot
  unconditionally, so one busy slot and a full house cost the same
  wall time — which means an estimate learned from solo warm-up
  traffic already predicts the full-house regime correctly (a naive
  aggregate tokens/sec EWMA learned solo under-reports capacity ~slots
  x and wrongly sheds the first real traffic: measured, and the bug
  this design replaces). The median, not a mean/EWMA, because the
  sample stream has structural outliers — a first-dispatch COMPILE is
  100-1000x a steady iteration, and one such sample in an EWMA biases
  predictions pessimistic for dozens of iterations (measured: wrong
  sheds at half the knee rate right after warm-up). The estimator
  stays unready until `min_samples` token-bearing iterations have
  landed: a cold estimator must never shed.

* **AdmissionController** — predicted completion for a new request =
  time to drain the work ahead at full-occupancy capacity
  (`backlog_units / (slots * tokens_per_slot / s_iter)`) plus the
  request's own service time (`own_units * s_iter / tokens_per_slot`).
  Work is counted in ITERATION-EQUIVALENT UNITS: generated tokens plus
  each request's prefill dispatches (one unit per prompt chunk in
  chunked mode, one for a one-shot prefill) — a slot consumes one
  scheduling iteration per unit, so the own-time term is structurally
  exact in plain mode and prefill-heavy backlogs no longer read as
  optimistically short (measured: ignoring prefill units produced
  mid-decode eviction thrash exactly in the marginal zone past the
  knee). A request is shed (`shed_predicted`) only when the prediction
  exceeds `conservatism` x its remaining deadline budget.
  `conservatism` >= 1 is the SHED-LATE knob: the estimator's errors
  must cost throughput (admitting a doomed request) before they may
  cost correctness (shedding a feasible one). On an idle server the
  backlog term vanishes and the own-time term approximates the solo
  total, so a request solo execution could finish in time — deadline
  at or above its solo latency — predicts within its budget by
  construction. tests/test_overload.py pins that invariant as a
  property test, and the decode server publishes every prediction's
  signed error (predicted - actual, ms) into the `admission_error_ms`
  histogram so a drifting estimator is visible on the Prometheus route
  before it is visible in shed counts.

* **BrownoutPolicy** — accept / DEFER / shed per request CLASS, driven
  by queue depth and recent SLO attainment. Brownout is the load-shape
  half admission prediction does not cover: prediction protects
  deadlines one request at a time; brownout protects the INTERACTIVE
  class as a matter of policy when the machine saturates (batch-class
  work parks in a deferred line that drains only when the primary
  queue is empty). Saturation behavior becomes an explicit object unit
  tests can enumerate, not an emergent accident of queue order.

`ContinuousDecodeServer(admission=..., brownout=...)` wires these in;
`tools/load_sweep.py --overload-ab` replays the PR 7 ladder with both
arms and pins goodput monotone past the knee.
"""
from __future__ import annotations

import collections
import threading

__all__ = ["ServiceRateEstimator", "AdmissionController",
           "BrownoutPolicy", "ACCEPT", "DEFER", "SHED", "PREEMPT"]

ACCEPT = "accept"
DEFER = "defer"
SHED = "shed"
PREEMPT = "preempt"


class ServiceRateEstimator:
    """Iteration-time + per-slot token-rate EWMAs (module docstring:
    iteration wall time is the occupancy-independent primitive — the
    slot program computes every slot unconditionally).

    `observe(tokens, dt, active)` is called once per scheduling
    iteration by the serve thread: `dt` feeds the iteration-time EWMA
    unconditionally (pure chunk-prefill passes lengthen iterations and
    must dilute capacity), `tokens / active` feeds the per-slot rate
    EWMA on token-bearing iterations (1.0 in plain decode, >1 under
    speculation). Predictions read both lock-free from client threads
    (float attribute reads are atomic under the GIL) and return None
    until `min_samples` token-bearing iterations have landed AND
    `slots` is known — the cold-start guard.

    `slots` is the scheduling width predictions scale capacity by; the
    decode server fills it in at construction when the caller left it
    None.

    VARIANCE-AWARE MARGIN (`margin`): under speculation the per-slot
    rate is 1..K tokens per iteration and swings with the workload's
    self-similarity — a few lucky high-acceptance iterations inflate
    the EWMA, the inflated rate admits marginal requests, acceptance
    reverts, and they die mid-decode (admit-then-evict thrash, the
    high-variance twin of the optimism the bias loop corrects —
    except the bias loop only learns AFTER evictions, while variance
    is visible BEFORE). Predictions therefore use a CONSERVATIVE rate:
    mean minus `margin` standard deviations (EWMA variance over the
    same samples), floored at 1.0 token/slot/iteration — the floor is
    structural, not a tuning: every decoding slot advances at least
    its bonus token per token-bearing iteration, so 1.0 is always
    achievable and the never-sheds-feasible-solo invariant survives
    any margin (a request whose deadline covers its worst-case
    1-token-per-iteration solo run predicts within budget by
    construction — pinned by property test in tests/test_overload.py).
    Plain decode has zero variance (every sample is exactly 1.0), so
    the margin is structurally free there. The `tokens_per_second`
    gauge keeps reporting the MEAN — it is the capacity/autoscaling
    read-out, not an admission decision."""

    def __init__(self, slots=None, alpha=0.2, min_samples=8, window=64,
                 margin=1.0):
        self.alpha = float(alpha)
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError(f"need 0 < alpha <= 1, got {alpha}")
        self.margin = float(margin)
        if self.margin < 0.0:
            raise ValueError(f"need margin >= 0, got {margin}")
        self.slots = None if slots is None else int(slots)
        self.min_samples = int(min_samples)
        self.samples = 0
        self._iters = collections.deque(maxlen=int(window))
        self._s_iter = None     # rolling MEDIAN of the window above
        self._tok_slot = None   # EWMA tokens per ACTIVE slot per iter
        self._tok_var = 0.0     # EWMA variance of the same samples
        # delivered-rate window: (tokens, dt) per iteration — the
        # MEASURED aggregate rate, chunk passes/churn/host contention
        # and all. Under confirmed overload this is the true capacity
        # (occupancy is full, so the occupancy bias that disqualifies
        # it for warm-up is gone) and the model above, which ignores
        # zero-token passes, overestimates — `predict_seconds(
        # saturated=True)` caps drain capacity by it.
        self._win = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()

    def observe(self, tokens, dt, active=0):
        """One scheduling iteration: `tokens` emitted across `active`
        decoding slots in `dt` seconds of wall time."""
        with self._lock:
            dt = max(float(dt), 0.0)
            self._iters.append(dt)
            srt = sorted(self._iters)
            n = len(srt)
            self._s_iter = (srt[n // 2] if n % 2 else
                            0.5 * (srt[n // 2 - 1] + srt[n // 2]))
            self._win.append((max(int(tokens), 0), dt))
            if tokens <= 0:
                return
            if active > 0:
                per_slot = tokens / float(active)
                if self._tok_slot is None:
                    self._tok_slot = per_slot
                else:
                    # EWMA mean + EWMA variance (deviation measured
                    # against the PRE-update mean — the standard
                    # exponentially-weighted pair)
                    dev = per_slot - self._tok_slot
                    self._tok_var = ((1.0 - self.alpha)
                                     * (self._tok_var
                                        + self.alpha * dev * dev))
                    self._tok_slot += self.alpha * dev
            self.samples += 1

    @property
    def delivered_tokens_per_second(self):
        """Measured aggregate rate over the iteration window (None
        while empty): every overhead included, occupancy NOT
        normalized — trustworthy only when the machine is known busy."""
        tok = dt = 0.0
        for t, d in list(self._win):
            tok += t
            dt += d
        return (tok / dt) if dt > 0 else None

    @property
    def ready(self):
        return (self.samples >= self.min_samples
                and self.slots is not None and bool(self._s_iter))

    @property
    def seconds_per_iteration(self):
        return self._s_iter if self.ready else None

    @property
    def tokens_per_second(self):
        """Full-occupancy capacity estimate (slots x per-slot rate /
        iteration time) — the `service_rate_tokens_per_sec` gauge.
        Reports the MEAN rate (the capacity/autoscaling read-out);
        admission predictions use the variance-margined rate below."""
        if not self.ready:
            return None
        return self.slots * (self._tok_slot or 1.0) / self._s_iter

    @property
    def tokens_per_slot_conservative(self):
        """The per-slot rate predictions divide by: EWMA mean minus
        `margin` EWMA standard deviations, floored at the structural
        1.0 token/slot/iteration worst case (every decoding slot lands
        at least its bonus token) and never above the mean. Plain
        decode: variance 0, so exactly the mean. None while no
        token-bearing sample has landed."""
        if self._tok_slot is None:
            return None
        pess = self._tok_slot - self.margin * (self._tok_var ** 0.5)
        return min(self._tok_slot, max(1.0, pess))

    def predict_seconds(self, backlog_tokens, own_tokens,
                        saturated=False):
        """Predicted seconds for a request with `own_tokens` to produce
        behind `backlog_tokens` of work ahead: drain the backlog at
        capacity, then (really: while) decode its own tokens one
        iteration each. None while cold. `saturated=True` (the server's
        confirmed-overload signal) caps drain capacity by the DELIVERED
        rate — under full occupancy that rate is ground truth, and the
        structural model, which never sees zero-token passes or host
        contention, reads high exactly when optimism turns into
        eviction thrash. The per-slot rate is the VARIANCE-MARGINED one
        (class docstring): high-variance acceptance widens predictions
        before it can thrash, and the 1.0 floor keeps the
        never-sheds-feasible-solo invariant for free."""
        if not self.ready:
            return None
        tps = self.tokens_per_slot_conservative or 1.0
        cap = self.slots * tps / self._s_iter
        if saturated:
            d = self.delivered_tokens_per_second
            if d:
                cap = min(cap, d)
        drain = float(backlog_tokens) / cap
        own = float(own_tokens) * self._s_iter / tps
        return drain + own


class AdmissionController:
    """Shed-at-enqueue decision: predicted completion vs deadline.

    `conservatism` scales the deadline budget the prediction is allowed
    to consume before shedding: 1.0 sheds exactly at the predicted
    miss, larger values shed later (the estimator must be MORE sure) —
    the knob the module docstring explains. The estimator is owned here
    so one controller can be shared/inspected; the decode server feeds
    it from the serve thread."""

    def __init__(self, conservatism=1.2, alpha=0.2, min_samples=8,
                 slots=None, bias_window=64, margin=1.0):
        self.conservatism = float(conservatism)
        if self.conservatism < 1.0:
            raise ValueError(f"conservatism must be >= 1.0 (shed late, "
                             f"never early), got {conservatism}")
        self.estimator = ServiceRateEstimator(slots=slots, alpha=alpha,
                                              min_samples=min_samples,
                                              margin=margin)
        # closed-loop bias correction: recent signed prediction errors
        # (predicted - actual; the decode server feeds completions and
        # eviction-time optimism BOUNDS). Only systematic OPTIMISM is
        # corrected — a negative median widens future predictions by
        # its magnitude, because optimism is the direction that admits
        # doomed requests (mid-decode eviction thrash, measured in the
        # marginal zone past the knee). Pessimistic drift is left to
        # the conservatism knob: correcting it would shrink
        # predictions, and a wrong shrink violates shed-late.
        self._errs = collections.deque(maxlen=int(bias_window))

    def observe_error(self, err_s):
        """One signed prediction-error sample in seconds (negative =
        optimistic). Fed by the decode server at request completion
        and, as a certain lower bound, at eviction/expiry."""
        self._errs.append(float(err_s))

    def bias_seconds(self):
        """Current optimism correction (>= 0): minus the median recent
        signed error when that median is negative, else 0."""
        errs = sorted(self._errs)
        n = len(errs)
        if n < 8:
            return 0.0
        med = errs[n // 2] if n % 2 else \
            0.5 * (errs[n // 2 - 1] + errs[n // 2])
        return max(0.0, -med)

    def predict_seconds(self, backlog_tokens, own_tokens,
                        saturated=False):
        """Predicted seconds until a request with `own_tokens` of its
        own budget, behind `backlog_tokens` of work ahead, completes —
        widened by the measured optimism bias; None while the estimator
        is cold."""
        p = self.estimator.predict_seconds(backlog_tokens, own_tokens,
                                           saturated=saturated)
        return None if p is None else p + self.bias_seconds()

    def should_shed(self, backlog_tokens, own_tokens, budget_s,
                    strict=False):
        """True when the prediction exceeds the allowed budget. A cold
        estimator never sheds; a request with no deadline is never shed
        (the caller passes budget_s=None).

        `strict` is the HYSTERESIS half of the conservatism contract:
        in the clear, predictions may consume `conservatism` x the
        budget before shedding (errors must cost throughput before
        correctness); once the server has CONFIRMED overload — actual
        evictions/queue expiries, not predictions (the decode server
        sets strict for a short window after each one) — the allowance
        drops to exactly 1.0 x budget, because every admitted
        predicted-miss in the [budget, conservatism x budget] band is
        now known to become eviction thrash, the precise waste this
        controller exists to prevent."""
        if budget_s is None:
            return False
        p = self.predict_seconds(backlog_tokens, own_tokens,
                                 saturated=strict)
        c = 1.0 if strict else self.conservatism
        return p is not None and p > c * max(float(budget_s), 0.0)


class BrownoutPolicy:
    """accept / defer / shed per request class at admission time.

    `classes` maps a class name to `(defer_at, shed_at)` queue-depth
    FRACTIONS (of the bounded submit queue): at or past defer_at the
    class parks in the deferred line (served only when the primary
    queue is empty — it yields to interactive work until pressure
    drops); at or past shed_at it is shed outright (`shed_brownout`).
    Classes not listed use `default`; the shipped default (1.01, 1.01)
    never defers or sheds, so an unconfigured class — and the decode
    server's implicit "default" class — behaves exactly as before the
    policy existed.

    `min_attainment`: when the server's RECENT SLO attainment (a
    rolling window the decode server maintains) drops below this, every
    class with defer_at <= 1 — i.e. any class that can defer at all
    (the never-defer default is 1.01) — defers regardless of queue
    depth: the attainment brownout. Depth measures pressure at the
    door, while attainment measures whether the machine is already
    failing the users inside."""

    def __init__(self, classes=None, default=(1.01, 1.01),
                 min_attainment=None):
        self.classes = {str(k): (float(d), float(s))
                        for k, (d, s) in (classes or {}).items()}
        self.default = (float(default[0]), float(default[1]))
        for name, (d, s) in list(self.classes.items()) + \
                [("default", self.default)]:
            if s < d:
                raise ValueError(f"class {name!r}: shed_at {s} < "
                                 f"defer_at {d} (defer must engage "
                                 f"first)")
        self.min_attainment = (None if min_attainment is None
                               else float(min_attainment))

    def may_preempt(self, victim_klass, claimant_klass):
        """The PREEMPT verb (durable KV state, serving/kvstate.py):
        True when a live `victim_klass` slot should yield its KV blocks
        to a `claimant_klass` request blocked on memory. The ranking is
        the one this policy already encodes: a class whose `defer_at`
        is STRICTLY below another's is the class that steps aside under
        queue pressure, so under MEMORY pressure it steps aside too —
        its work is spilled to host (resumable bit-identically), not
        thrown away, which is what bounds interactive TTFT at full
        block occupancy where queue-depth admission structurally
        cannot. Equal-rank classes never preempt each other (no
        same-class churn), and the shipped never-defer default (1.01)
        can never be a victim of another default-class request."""
        vd = self.classes.get(str(victim_klass), self.default)[0]
        cd = self.classes.get(str(claimant_klass), self.default)[0]
        return vd < cd

    def decide(self, klass, queue_frac, attainment=None):
        """One admission decision: ACCEPT, DEFER, or SHED."""
        defer_at, shed_at = self.classes.get(str(klass), self.default)
        if queue_frac >= shed_at:
            return SHED
        if queue_frac >= defer_at:
            return DEFER
        if (self.min_attainment is not None and attainment is not None
                and attainment < self.min_attainment and defer_at <= 1.0):
            return DEFER
        return ACCEPT
