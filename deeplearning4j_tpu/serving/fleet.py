"""Replica fleet manager: the observability plane's tested ACTUATOR.

PR 12 built the fleet's sensors — kind-correct metrics federation
(`obs.fleet.FleetView`), stitched traces, and `AutoscaleSignal`, the
hysteresis-bounded scale detector. This module closes the loop: a
`FleetManager` owns N in-process `ContinuousDecodeServer` replicas
behind a router and ACTS on what the sensors say.

  * **Router** — the PR 12 round-robin splitter promoted into the
    package (`RoundRobinSplitter` stays available as the deliberately
    dumb baseline/A-B arm) and grown into a real front door:
    least-backlog dispatch over ALIVE replicas, a per-replica health
    state machine (healthy -> degraded -> dead, driven by each
    replica's `ServingMetrics` shed/failure deltas and a serve-thread
    liveness probe), and `RetryPolicy`-bounded resubmission on
    failover. The manager's `submit()` future is the caller's ONE
    handle: it resolves with the token stream no matter which replica
    (or how many, across failovers) produced it. The control plane is
    host-side only — the no-fault fleet path adds ZERO device
    dispatches per token over N bare servers (dispatch-counter A/B,
    tests/test_fleet_manager.py).

  * **Prefix-affinity routing + the fleet prefix tier** — the
    `affinity` policy consistent-hashes each request's block-aligned
    leading prompt tokens over alive replicas (vnode ring, ~1/N keys
    remap per replica churned) so a shared-system-prompt family keeps
    hitting ONE replica's warm prefix cache at any fleet size, with a
    load-aware spill rule (`spill_factor`/`spill_slack`) that falls to
    the least-backlog survivor — counted `routed_affinity` /
    `routed_spill` — before stickiness becomes a hotspot. When a key
    routes somewhere the manager believes cold while a peer is warm,
    an async PREFIX_PULL ships the peer's resident chain
    (`PrefixCacheArtifact` over the existing wire frames, tag-checked
    at adoption) into the cold replica instead of recomputing it —
    off the dispatch path, budget-bounded at both ends, so the
    no-pull path adds ZERO device dispatches per token.

  * **Closed autoscale loop** — each `control_tick()` federates every
    replica's `kind_snapshot()` into one fleet snapshot, feeds it to
    the `AutoscaleSignal`, and ACTS: `scale_up` spawns a fresh replica
    (factory-built, warmed, fleet-unique instance id — ids are NEVER
    reused, so federation and traces can never alias a dead replica
    with its successor); `scale_down` gracefully drains one —
    `drain(migrate=True)` moves its live decode-phase requests to
    survivors as `RequestArtifact`s (resumed streams bit-identical,
    the durable-KV pin exercised across the router) and replays its
    queued/prefilling requests from their prompts. After every action
    the signal resets: the next move must be argued entirely from
    observations of the NEW fleet shape. The detector's scale_down
    occupancy input is the manager-computed UTILIZATION (delivered
    tokens/s over the tick window / fleet capacity): the per-replica
    occupancy reservoirs are iteration-weighted and no iterations run
    at idle, so a quiet fleet would otherwise never read as idle.

  * **Health-gated canary rollout** — `rollout(new_lm)` screens the
    new params with `rowwise_finite` FIRST (a NaN/Inf leaf rolls back
    before any replica — and therefore any request — ever touches the
    poisoned weights), then hot-swaps ONE canary replica and watches
    it over a probation window: failure/unhealthy-output deltas, SLO
    attainment, and shed deltas vs the survivors. A tripped gate swaps
    the canary back (`canary_rollbacks` counted) — version-tagged
    params mean the prefix index and admission already cooperate, and
    the dual-version drain keeps every in-flight request alive through
    both the swap and the rollback. A passing gate rolls forward
    replica by replica; future spawns inherit the new params.

  * **Crash survival** — `FaultInjector` sites: `fleet.submit` (fired
    per routed request — a raising rule is a router fault) and
    `fleet.replica` (fired once per alive replica per control tick;
    the SEVER action is replica death mid-stream — it lands on
    `ContinuousDecodeServer.kill()`, which fails every in-flight
    future loudly with `ReplicaDeadError`). The router marks the
    replica dead, takes a final counters-only snapshot (a TOMBSTONE,
    so federated counters stay monotone after the instance is gone),
    and resubmits the dead replica's in-flight requests to survivors
    via prompt replay: deterministic greedy decode makes the replayed
    stream bit-identical to an uninterrupted solo run, so a crash
    costs latency, never bits — and never a silently lost future
    (every admitted future resolves: completed via failover replay or
    failed loudly with a named error). The autoscale loop backfills
    capacity: `control_tick()` re-spawns up to `min_replicas` before
    consulting the signal.

  * **Blast-radius containment** (ARCHITECTURE.md has the full rules):
    three disciplines that keep one bad request, one bad config, or
    one overload wave from taking the whole fleet down. (1) POISON-
    PILL QUARANTINE: the manager records which replica deaths each
    in-flight request was aboard for; a request implicated in
    `_QUARANTINE_DEATHS` distinct deaths is the probable killer — its
    outer future fails with `PoisonPillError` (never replayed again),
    its prompt fingerprint enters a bounded quarantine set that sheds
    re-submissions at admission, and the event is journaled so
    `recover()` doesn't resurrect it. (2) SPAWN CIRCUIT BREAKER:
    a replica dying within `infant_mortality_s` of spawn is a strike;
    K consecutive strikes OPEN the breaker — backfill stops crash-
    looping and probes with ONE spawn per exponential-backoff window
    (half-open) until a probe survives infancy. While open the fleet
    runs DEGRADED: it serves on the replicas it has and sheds the
    lowest request classes via the `BrownoutPolicy` seam, so
    accounting (admitted == completed + failed) holds with less
    capacity. (3) FLEET-WIDE RETRY BUDGET: failover replays and wire
    resends share one `RetryBudget` token bucket (refilled as a
    fraction of completions); exhaustion converts the retry into a
    loud `RetryBudgetExhaustedError` instead of amplifying load — the
    metastable-failure guard.

The manager itself publishes the fleet-control event counters —
`replica_spawned` / `replica_drained` / `replica_dead` /
`failover_resubmitted` / `canary_rollbacks` — plus the containment
counters (`requests_quarantined` / `breaker_open_total` /
`retry_budget_exhausted` / `degraded_mode_ticks` / `infant_deaths`
and the `breaker_state` gauge) — through its own `ServingMetrics`
(always-present snapshot keys, on the Prometheus route like every
other endpoint) and overlays them onto `fleet_snapshot()` as
`fleet_*` keys next to the PR 12 federation read-outs.
"""
from __future__ import annotations

import bisect
import collections
import concurrent.futures as cf
import hashlib
import itertools
import logging
import os
import threading
import time

from ..common.resilience import (RetryBudgetExhaustedError, RetryPolicy)
from ..obs.fleet import SHED_KEYS, AutoscaleSignal, FleetView
from .admission import SHED as BROWNOUT_SHED
from .fleetjournal import FleetJournal, fold_records, replay_journal
from .kvstate import KVStateError, KVStateVersionError
from .metrics import ServingMetrics
from .server import (DeadlineExceededError, PoisonPillError,
                     ReplicaDeadError, ServerClosedError,
                     ServerOverloadedError, UnhealthyOutputError,
                     _fail_future, _ParamsView, _resolve_future)

log = logging.getLogger(__name__)

__all__ = ["FleetManager", "RoundRobinSplitter", "HEALTHY", "DEGRADED",
           "DRAINING", "DEAD", "BREAKER_CLOSED", "BREAKER_OPEN",
           "BREAKER_HALF_OPEN"]

# replica health states (the router's per-replica state machine):
# HEALTHY and DEGRADED are routable (healthy preferred), DRAINING
# takes no new work while its requests move out, DEAD is a tombstone
HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
DEAD = "dead"

# spawn circuit-breaker states: CLOSED spawns freely, OPEN refuses
# (degraded mode), HALF_OPEN has exactly one probe spawn in flight.
# The `breaker_state` gauge publishes them as 0 / 1 / 0.5.
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0.0, BREAKER_HALF_OPEN: 0.5,
                  BREAKER_OPEN: 1.0}

# distinct replica deaths that convict an in-flight request as the
# poison pill (one death has too many innocent co-victims; two
# distinct replicas dying under the same request is the signature)
_QUARANTINE_DEATHS = 2


def _fingerprint(prompt, params_version):
    """Quarantine identity of a request: sha256 over the prompt tokens
    + the params version they would decode under (the same prompt is a
    DIFFERENT request against different weights)."""
    payload = repr((tuple(int(t) for t in prompt),
                    int(params_version or 0))).encode()
    return hashlib.sha256(payload).hexdigest()


# consistent-hash ring (the affinity policy): each replica owns
# `_RING_VNODES` pseudo-random points on a 64-bit circle; a key routes
# to the first replica point clockwise of its own hash. Adding or
# removing ONE replica moves only the arcs adjacent to its points —
# ~1/N of the key space — so fleet churn never reshuffles (and thereby
# cold-starts) every replica's warm prefix cache at once. Module-level
# and stdlib-pure so the ring-stability property test drives them
# directly.
_RING_VNODES = 64


def _ring_hash(data):
    """Stable 64-bit point on the ring (sha256, never `hash()` — the
    per-process randomization would reshuffle placement every run)."""
    if not isinstance(data, bytes):
        data = repr(data).encode()
    return int.from_bytes(hashlib.sha256(data).digest()[:8], "big")


def _build_ring(names, vnodes=_RING_VNODES):
    """Sorted (point, name) list over `names`, `vnodes` points each."""
    ring = []
    for name in names:
        for v in range(vnodes):
            ring.append((_ring_hash(f"{name}:{v}".encode()), name))
    ring.sort()
    return ring


def _ring_lookup(ring, keyhash, exclude=()):
    """First owner clockwise of `keyhash` whose name is not excluded
    (None on an empty/fully-excluded ring)."""
    if not ring:
        return None
    i = bisect.bisect_left(ring, (keyhash, ""))
    for off in range(len(ring)):
        _, name = ring[(i + off) % len(ring)]
        if name not in exclude:
            return name
    return None


class RoundRobinSplitter:
    """The PR 12 fleet front door, promoted from tools/load_sweep.py:
    submit() rotates over N replicas. Deliberately dumb — observability
    sweeps measure the fleet plane, not placement policy, and the
    FleetManager's zero-added-dispatch A/B compares against exactly
    this (a shed at one replica is a fleet shed, both arms)."""

    def __init__(self, servers):
        self._servers = list(servers)
        self._i = 0

    def submit(self, prompt, max_new, **kw):
        srv = self._servers[self._i % len(self._servers)]
        self._i += 1
        return srv.submit(prompt, max_new, **kw)


def _params_finite(lm):
    """The canary NaN/Inf screen: every float leaf of (aux, blocks)
    all-finite, via the SAME `rowwise_finite` helper the serving output
    screen uses (each leaf flattened to one row). Host-side numpy on
    weights that are about to be shipped to N replicas anyway."""
    import numpy as np

    import jax

    from ..common.health import rowwise_finite
    leaves = jax.tree_util.tree_leaves((lm.aux, lm.blocks))
    ok = rowwise_finite([np.asarray(leaf).reshape(1, -1)
                         for leaf in leaves])
    return ok is None or bool(ok.all())


class _FleetRequest:
    """Manager-side record of one admitted request: the caller-facing
    OUTER future plus everything a failover replay needs."""

    __slots__ = ("prompt", "max_new", "deadline", "klass", "outer",
                 "attempts", "replica", "deaths", "fp", "akey")

    def __init__(self, prompt, max_new, deadline, klass, fp=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.deadline = deadline        # absolute monotonic, or None
        self.klass = klass
        self.outer = cf.Future()
        self.attempts = 0               # failover resubmissions so far
        self.replica = None             # current replica name
        self.deaths = set()             # replica deaths it was aboard for
        self.fp = fp                    # quarantine fingerprint
        self.akey = None                # block-aligned affinity key


class _Replica:
    __slots__ = ("name", "server", "state", "seq", "inflight",
                 "probe_sheds", "probe_failed", "born", "keys_seen")

    def __init__(self, name, server, seq, born=None):
        self.name = name
        self.server = server
        self.state = HEALTHY
        self.seq = seq                  # spawn order (tie-breaks)
        self.inflight = 0               # manager-tracked live requests
        self.probe_sheds = 0            # health probe baselines
        self.probe_failed = 0
        self.keys_seen = collections.OrderedDict()  # affinity keys
        #   routed here (bounded) — the manager's believed-warm set
        #   that decides when a prefix pull is worth scheduling
        self.born = born                # spawn monotonic (None: adopted
        #                                 — an adoptee's age is unknown,
        #                                 so it can never strike the
        #                                 spawn breaker as an infant)


class FleetManager:
    """N replicas, one front door, three closed loops (module
    docstring).

    `factory(name)` builds ONE replica (a `ContinuousDecodeServer`,
    running or not — the manager starts it) under the fleet-unique
    instance `name` the manager mints; it is called for the initial
    `n_replicas` at `start()` and again on every scale_up/backfill.
    `warmup(server)` (optional) runs after each spawn — compile the
    prompt buckets off the serving clock there.

    `signal` is the `AutoscaleSignal` `control_tick()` consults (None:
    no autoscaling — the manager is a router + failover only, which is
    exactly what the observe-only sweeps want). `policy` is
    "least_backlog" (default), "round_robin" (the A/B arm), or
    "affinity": consistent-hash the request's block-aligned leading
    prompt tokens (`affinity_blocks` x `affinity_block` of them — the
    shared-system-prompt identity) over alive replicas so one prompt
    family always lands on one replica's warm prefix cache, with a
    load-aware SPILL — when the affine replica's backlog exceeds
    `spill_factor` x the fleet minimum + `spill_slack`, the request
    falls to the least-backlog survivor instead (`routed_spill`
    counted; the sticky choice must never become a hotspot SLO leak).
    With `prefix_pull` (default), routing a key to a replica the
    manager believes cold while a peer is warm schedules an async
    PREFIX_PULL of the peer's resident chain (off the dispatch path,
    bounded fleet-wide by `prefix_pull_budget_bytes`) — the spilled/
    remapped replica adopts the blocks instead of recomputing them.
    """

    # request-level VERDICTS settle the outer future as-is; everything
    # else is infrastructure and fails over. RequestMigratedError /
    # RequestDrainedError are deliberately NOT verdicts here: on an
    # inner future they only ever mean the request's state moved (the
    # manager's own drain, or an out-of-band operator migrate racing
    # it) — replaying on a survivor still yields the correct stream,
    # while propagating would fail the caller with a handoff-protocol
    # internal on e.g. a drain that completed just after its timeout.
    _PROPAGATE = (DeadlineExceededError, ServerOverloadedError,
                  UnhealthyOutputError, RetryBudgetExhaustedError,
                  ValueError)

    def __init__(self, factory, n_replicas=2, *, signal=None,
                 policy="least_backlog", min_replicas=None,
                 max_replicas=None, retry_policy=None,
                 heartbeat_timeout=None, fault_injector=None,
                 metrics=None, name="fleet", warmup=None,
                 degrade_shed_rate=25, name_prefix="i",
                 journal=None, retry_budget=None, brownout=None,
                 kill_hook=None, infant_mortality_s=5.0,
                 breaker_strikes=3, breaker_backoff_s=0.5,
                 breaker_max_backoff_s=30.0, quarantine_capacity=256,
                 journal_compact_bytes=None, affinity_block=8,
                 affinity_blocks=1, spill_factor=2.0, spill_slack=4,
                 prefix_pull=True, prefix_pull_budget_bytes=64 << 20):
        if policy not in ("least_backlog", "round_robin", "affinity"):
            raise ValueError(f"unknown routing policy {policy!r}")
        if int(n_replicas) < 1:
            raise ValueError("need n_replicas >= 1")
        if int(breaker_strikes) < 1:
            raise ValueError("need breaker_strikes >= 1")
        self._factory = factory
        self._n_initial = int(n_replicas)
        self.signal = signal
        self._policy = policy
        self.min_replicas = (int(min_replicas) if min_replicas is not None
                             else self._n_initial)
        self.max_replicas = (int(max_replicas) if max_replicas is not None
                             else self._n_initial + 4)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}")
        # failover budget + pacing: the policy bounds resubmissions per
        # request; classification (what IS a failover vs a request
        # verdict) is the manager's explicit table, not `retryable`.
        # Both are PUBLIC wire config too: remote replicas
        # (serving/wire.py RemoteReplica) inherit the retry policy for
        # reconnect-with-resend and `heartbeat_timeout` for the
        # ack-silence reap that feeds the healthy→degraded→dead state
        # machine (`_spawn` pushes them through `configure_wire`).
        self._retry = retry_policy if retry_policy is not None else \
            RetryPolicy(max_retries=3, base_delay=0.0, jitter=0.0)
        # the fleet-wide retry budget rides ON the retry policy (the
        # shared hook): the same object `configure_wire` hands every
        # remote replica, so wire resends and failover replays spend
        # from ONE bucket
        if retry_budget is not None:
            self._retry.budget = retry_budget
        self.heartbeat_timeout = (None if heartbeat_timeout is None
                                  else float(heartbeat_timeout))
        self._injector = fault_injector
        self.metrics = metrics or ServingMetrics(name=name)
        self.name = name
        self._warmup = warmup
        self.degrade_shed_rate = float(degrade_shed_rate)
        self._lock = threading.RLock()
        self._replicas = collections.OrderedDict()   # name -> _Replica
        self._tombstones = collections.OrderedDict()  # name -> counters
        self._live = {}             # inner future -> _FleetRequest
        self._name_ids = itertools.count()
        self._name_prefix = str(name_prefix)
        self._seq = itertools.count()
        self._rr = 0                # round-robin rotation
        # prefix-affinity routing (module docstring): the consistent-
        # hash ring over alive replicas, the block geometry of the
        # affinity key, the load-aware spill rule, and the fleet
        # prefix tier's pull budget/in-flight dedup
        self.affinity_block = int(affinity_block)
        self.affinity_blocks = int(affinity_blocks)
        if self.affinity_block < 1 or self.affinity_blocks < 1:
            raise ValueError("need affinity_block >= 1 and "
                             "affinity_blocks >= 1")
        self.spill_factor = float(spill_factor)
        self.spill_slack = int(spill_slack)
        if self.spill_factor < 1.0 or self.spill_slack < 0:
            raise ValueError("need spill_factor >= 1.0 and "
                             "spill_slack >= 0")
        self.prefix_pull = bool(prefix_pull)
        self._pull_budget_left = int(prefix_pull_budget_bytes)
        self._pulls_inflight = set()    # (dst name, key) being pulled
        self._ring = []                 # sorted (point, name)
        self._ring_names = ()           # roster the ring was built for
        self._keys_seen_cap = 512       # per-replica believed-warm cap
        self._running = False
        self._rolling = False       # a rollout is mid-probation:
        #                             control_tick holds scale actions
        self._params = None         # (aux, blocks) spawns must carry
        #                             (set by a rolled-forward rollout)
        self._ctl_thread = None
        self._ctl_stop = threading.Event()
        self._ticks = 0
        self._last_tick = None      # (monotonic, fleet tokens_out) —
        #                             the utilization window
        # blast-radius containment state (module docstring):
        # quarantine — bounded ordered set of poison fingerprints
        self._quarantine = collections.OrderedDict()
        self._quarantine_cap = int(quarantine_capacity)
        # spawn circuit breaker — strike counter + state machine
        self.infant_mortality_s = float(infant_mortality_s)
        self.breaker_strikes = int(breaker_strikes)     # K
        self._breaker = BREAKER_CLOSED
        self._strikes = 0
        self._last_strike = 0.0     # monotonic of the latest strike:
        #                             a spawn born after it that
        #                             survives infancy breaks the
        #                             CONSECUTIVE-strike chain
        self._breaker_backoff0 = float(breaker_backoff_s)
        self._breaker_backoff = float(breaker_backoff_s)
        self._breaker_max_backoff = float(breaker_max_backoff_s)
        self._breaker_until = 0.0   # monotonic: next half-open probe
        self._probe_name = None     # the one in-flight probe replica
        # degraded-mode brownout (None: degraded mode serves what it
        # can but sheds nothing — the legacy behavior)
        self._brownout = brownout
        # chaos seam: kill_hook(prompt, replica_name) -> truthy crashes
        # the replica the request just landed on (a poison decode)
        self._kill_hook = kill_hook
        self._journal_compact_bytes = (
            None if journal_compact_bytes is None
            else int(journal_compact_bytes))
        # durable control plane (serving/fleetjournal.py): `journal`
        # (a path) makes every state transition a fsync'd WAL record.
        # Each manager GENERATION bumps the monotone epoch past
        # whatever the journal already holds — a successor recovering
        # from the same file outranks (and fences out) its
        # predecessor; minted names resume PAST the journaled ones so
        # instance ids stay fleet-unique across generations.
        self._journal = None
        self._params_version = 0
        self.epoch = 0
        if journal is not None:
            prior = fold_records(replay_journal(journal),
                                 name_prefix=self._name_prefix)
            self.epoch = prior["epoch"] + 1
            self._params_version = prior["params_version"] or 0
            if prior["max_id"] >= 0:
                self._name_ids = itertools.count(prior["max_id"] + 1)
            # containment state survives the manager: quarantined
            # fingerprints keep shedding (recover() must not resurrect
            # the killer) and an OPEN breaker stays open (the successor
            # must not resume the spawn crash-loop its predecessor
            # escaped — it probes after a fresh backoff instead)
            for fp in prior.get("quarantine") or ():
                self._quarantine[fp] = True
            while len(self._quarantine) > self._quarantine_cap:
                self._quarantine.popitem(last=False)
            br = prior.get("breaker")
            if br and br.get("state") in (BREAKER_OPEN,
                                          BREAKER_HALF_OPEN):
                self._breaker = BREAKER_OPEN
                self._strikes = int(br.get("strikes") or
                                    self.breaker_strikes)
                self._breaker_backoff = min(
                    self._breaker_max_backoff,
                    float(br.get("backoff_s") or self._breaker_backoff))
                self._breaker_until = (time.monotonic()
                                       + self._breaker_backoff)
                self.metrics.record_breaker_state(
                    _BREAKER_GAUGE[BREAKER_OPEN])
            self._journal = FleetJournal(journal, counters=self.metrics)
            self._journal.append("epoch", epoch=self.epoch)
            # counter == this manager's generation (bumped by delta so
            # a reused metrics sink stays monotone)
            cur = self.metrics.count_value("manager_epoch")
            if self.epoch > cur:
                self.metrics.count("manager_epoch", self.epoch - cur)

    def _journal_append(self, kind, **fields):
        """Best-effort durable record of one state transition: journal
        failures must never take a crash/drain path down with them
        (several run on done-callback threads) — they log loudly and
        the fleet keeps serving."""
        j = self._journal
        if j is None:
            return
        try:
            j.append(kind, epoch=self.epoch, **fields)
        except Exception:   # noqa: BLE001 — the WAL is not the fleet
            log.exception("fleet journal append failed (%s)", kind)

    # -- lifecycle -----------------------------------------------------
    def start(self, control_interval_s=None):
        """Spawn the initial replicas (idempotent) and, with
        `control_interval_s`, a daemon control thread running
        `control_tick()` on that cadence. Tests and the sweep drive
        ticks manually instead. Each guard is independent: a manager
        that is already running (e.g. built by `recover()`, which
        reconciles its own roster) still gets its control thread here,
        but never a second one."""
        if not self._running:
            self._running = True
            while self.n_alive() < self._n_initial:
                self._spawn()
        if control_interval_s is not None and self._ctl_thread is None:
            self._ctl_stop.clear()

            def _loop():
                while not self._ctl_stop.wait(float(control_interval_s)):
                    try:
                        self.control_tick()
                    except Exception:   # noqa: BLE001 — keep ticking
                        log.exception("control tick failed")

            self._ctl_thread = threading.Thread(
                target=_loop, name="fleet-control", daemon=True)
            self._ctl_thread.start()
        return self

    def stop(self, drain=True, timeout=60.0):
        """Stop the control loop and every replica. drain=True lets
        each replica serve what it already admitted; drain=False fails
        queued work (`ServerClosedError`) — either way every manager
        future resolves (the replicas' own stop contracts + the
        failover path's not-running check)."""
        self._running = False
        self._ctl_stop.set()
        t = self._ctl_thread
        if t is not None:
            t.join(timeout)
            self._ctl_thread = None
        stopped = set()
        while True:
            with self._lock:
                recs = [r for r in self._replicas.values()
                        if r.name not in stopped]
            if not recs:
                break       # second sweep: a spawn that was mid-flight
            #                 when _running dropped still gets stopped
            for rec in recs:
                stopped.add(rec.name)
                try:
                    rec.server.stop(drain=drain, timeout=timeout)
                except Exception:   # noqa: BLE001 — teardown finishes
                    log.exception("replica %s stop failed", rec.name)
                # a cleanly stopped replica leaves the durable roster:
                # a successor recovering this journal must not re-dial
                # (or backfill-count) what this generation shut down
                self._journal_append("replica_drained", name=rec.name,
                                     reason="manager stop")
        j, self._journal = self._journal, None
        if j is not None:
            try:
                j.append("manager_stop", epoch=self.epoch)
                j.close()
            except Exception:   # noqa: BLE001 — teardown finishes
                log.exception("fleet journal close failed")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @classmethod
    def recover(cls, factory, journal_path, *, redial=None,
                params_lm=None, identity_dir=None, backfill=True,
                control_interval_s=None, **kwargs):
        """Build a SUCCESSOR manager from a predecessor's journal: the
        durable-control-plane recovery path (module docstring; the
        reconcile rules live in ARCHITECTURE.md).

        Replays `journal_path` (a mid-file corruption refuses loudly
        with `JournalCorruptError`; a torn final record is a crash
        artifact and drops silently), folds it to the intended roster,
        then reconciles against reality:

          * every listed replica is re-dialed (`redial(name, ident)` —
            default: a fresh `RemoteReplica` to the journaled
            host:port) and its HELLO identity VERIFIED against the
            journal (instance name, pid, process start-time): a
            recycled port owned by an unrelated process is refused
            loudly (`adopt_identity_mismatch` counted, local-only
            teardown — never a KILL frame at a stranger) instead of
            adopted;
          * verified replicas are RE-ADOPTED (`replicas_adopted`
            counted): router, health probe, federation and in-flight
            accounting resume, and the new generation's epoch is
            announced so the predecessor is fenced out;
          * with `identity_dir`, a listed replica whose identity file
            is GONE exited cleanly (`run_replica_server` removes it on
            graceful exit) and is skipped without a dial;
          * a replica mid-drain (`drain_begin` with no completion) is
            never re-adopted — its predecessor was emptying it; it is
            put down best-effort and backfilled;
          * a half-finished canary (`canary_begin` with no verdict)
            rolls back DETERMINISTICALLY: the canary alone holds
            unvetted params, so it is crashed (`canary_rollbacks`
            counted) and the backfill rebuilds it on known-good
            factory params;
          * dead/unreachable listed replicas — and, with `backfill`
            (default), any capacity shortfall — are backfilled to
            `min_replicas` through the normal spawn path.

        `params_lm` (optional) restores the rolled-forward parameter
        set for FUTURE spawns when the journal records a fleet-wide
        roll-forward; `control_interval_s` (optional) starts the
        periodic control thread exactly as `start()` would — and a
        later `mgr.start(control_interval_s=...)` on the recovered
        manager does the same (`kwargs` pass through to the
        constructor). Returns the running successor — its epoch is the
        journal's highest + 1, its minted names resume past the
        journal's."""
        records = replay_journal(journal_path)
        intent = fold_records(records,
                              name_prefix=kwargs.get("name_prefix", "i"))
        mgr = cls(factory, journal=journal_path, **kwargs)
        mgr._running = True
        if redial is None:
            def redial(name, ident):
                from .wire import RemoteReplica
                if not ident.get("port"):
                    raise ConnectionError(
                        f"no wire identity journaled for {name!r}")
                return RemoteReplica(ident.get("host") or "127.0.0.1",
                                     ident["port"])
        roster = sorted(intent["roster"].items(),
                        key=lambda kv: (kv[1].get("seq") or 0, kv[0]))
        for name, ident in roster:
            if ident.get("draining"):
                # the predecessor was emptying it: routing new work
                # there would resurrect a replica mid-goodbye — put it
                # down best-effort and let the backfill replace it
                try:
                    srv = redial(name, ident)
                    srv.kill()
                except Exception:   # noqa: BLE001 — already gone
                    pass
                mgr._journal_append("replica_dead", name=name,
                                    reason="mid-drain at recovery")
                continue
            if identity_dir is not None and not os.path.exists(
                    os.path.join(str(identity_dir), f"{name}.json")):
                # graceful exits remove their identity file: nothing
                # crashed, nothing to re-adopt, nothing to put down
                mgr._journal_append("replica_drained", name=name,
                                    reason="clean exit before recovery")
                continue
            try:
                srv = redial(name, ident)
            except Exception as e:  # noqa: BLE001 — dead is dead
                mgr._journal_append(
                    "replica_dead", name=name,
                    reason=f"unreachable at recovery: {e}")
                continue
            inst = getattr(srv, "instance", None)
            pid = getattr(srv, "pid", None)
            st = getattr(srv, "start_time", None)
            mismatch = (
                (inst is not None and inst != name)
                or (ident.get("pid") is not None and pid is not None
                    and pid != ident["pid"])
                or (ident.get("start_time") is not None
                    and st is not None
                    and st != ident["start_time"]))
            if mismatch:
                # a recycled port: whoever answered is NOT the replica
                # the journal listed. Refuse loudly, tear down the
                # local wire half ONLY — a KILL/STOP frame would drive
                # an unrelated process
                mgr.metrics.count("adopt_identity_mismatch")
                log.error(
                    "re-adoption of %s refused: identity mismatch "
                    "(instance %r pid %r start %r vs journaled "
                    "%r/%r/%r)", name, inst, pid, st, name,
                    ident.get("pid"), ident.get("start_time"))
                if hasattr(srv, "_shutdown_local"):
                    srv._shutdown_local(ServerClosedError(
                        "identity mismatch at re-adoption"), dead=False)
                mgr._journal_append("replica_dead", name=name,
                                    reason="identity mismatch")
                continue
            if hasattr(srv, "configure_wire"):
                # announcing the successor's epoch HERE is what fences
                # the predecessor out of this replica
                srv.configure_wire(
                    heartbeat_timeout=mgr.heartbeat_timeout,
                    retry_policy=mgr._retry, counters=mgr.metrics,
                    epoch=mgr.epoch or None)
            with mgr._lock:
                rec = _Replica(name, srv, next(mgr._seq))
                mgr._replicas[name] = rec
            mgr.metrics.count("replicas_adopted")
            mgr._journal_append(
                "adopt", name=name, seq=rec.seq,
                host=ident.get("host"), port=ident.get("port"),
                pid=pid if pid is not None else ident.get("pid"),
                start_time=st if st is not None
                else ident.get("start_time"))
            log.info("replica %s re-adopted (epoch %d)", name,
                     mgr.epoch)
        can = intent["canary"]
        if can is not None:
            # mid-probation death: the canary alone holds params no
            # gate ever vetted — deterministic rollback by crash (the
            # backfill below rebuilds on known-good factory params)
            mgr.metrics.count("canary_rollbacks")
            mgr._journal_append("canary_rolled_back",
                                name=can.get("name"),
                                reason="manager died mid-probation")
            with mgr._lock:
                adopted_canary = can.get("name") in mgr._replicas
            if adopted_canary:
                mgr._crash(can["name"],
                           reason="canary rollback at recovery",
                           convict=False)
        if intent["params_version"] and params_lm is not None:
            mgr._params = (params_lm.aux, params_lm.blocks)
        if backfill:
            # BOUNDED: spawns that succeed but die before the next
            # n_alive() read (an infant-death factory) must not loop
            # this path forever — cap at min_replicas + K attempts,
            # respect the (possibly inherited-open) breaker, and fall
            # through to degraded mode with a warning
            for _ in range(mgr.min_replicas + mgr.breaker_strikes):
                if mgr.n_alive() >= mgr.min_replicas:
                    break
                if not mgr._spawn_allowed():
                    break
                mgr._spawn_guarded()
            if mgr.n_alive() < mgr.min_replicas:
                log.warning(
                    "recovery backfill stopped at %d/%d alive "
                    "replicas (breaker %s): degraded mode",
                    mgr.n_alive(), mgr.min_replicas, mgr._breaker)
        if control_interval_s is not None:
            mgr.start(control_interval_s=control_interval_s)
        return mgr

    # -- introspection -------------------------------------------------
    def n_alive(self):
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.state in (HEALTHY, DEGRADED))

    @property
    def replicas(self):
        """Alive replica names, spawn order."""
        with self._lock:
            return [r.name for r in self._replicas.values()
                    if r.state in (HEALTHY, DEGRADED)]

    def states(self):
        """name -> health state, every replica ever (tombstones DEAD)."""
        with self._lock:
            out = {r.name: r.state for r in self._replicas.values()}
            for name in self._tombstones:
                out.setdefault(name, DEAD)
            return out

    def replica(self, name):
        """The live server object (ops/test hook)."""
        with self._lock:
            return self._replicas[name].server

    # -- client API ----------------------------------------------------
    def submit(self, prompt, max_new_tokens, deadline_ms=None,
               klass="default"):
        """Enqueue one decode request on the best alive replica;
        returns the MANAGER's future — it survives replica death,
        drains, and rollouts (the inner replica future is an
        implementation detail). Synchronous sheds at the chosen replica
        propagate (a shed at one replica is a fleet shed — the caller
        owns retry policy for overload, the manager only owns
        failover)."""
        if not self._running:
            raise ServerClosedError("fleet manager is not running")
        if self._injector is not None:
            self._injector.fire("fleet.submit")
        fp = _fingerprint(prompt, self._params_version)
        with self._lock:
            quarantined = fp in self._quarantine
        if quarantined:
            # a re-submission of a convicted poison pill: shed at the
            # door — it must never reach (and kill) another replica
            self.metrics.count("requests_quarantined")
            raise PoisonPillError(
                f"prompt fingerprint {fp[:12]} is quarantined "
                f"(implicated in >= {_QUARANTINE_DEATHS} replica "
                f"deaths)")
        if self._breaker != BREAKER_CLOSED and \
                self._brownout is not None:
            # degraded mode: the breaker says capacity cannot be
            # rebuilt right now, so the brownout seam sheds the lowest
            # classes first — pressure is the missing-capacity
            # fraction, standing in for the queue fraction the
            # per-server policy uses
            pressure = max(0.0, 1.0 - self.n_alive()
                           / max(1, self.min_replicas))
            if self._brownout.decide(klass, pressure) == BROWNOUT_SHED:
                self.metrics.count("shed_brownout")
                raise ServerOverloadedError(
                    f"degraded mode (spawn breaker {self._breaker}): "
                    f"class {klass!r} shed by fleet brownout")
        now = time.monotonic()
        deadline = (now + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        req = _FleetRequest(prompt, max_new_tokens, deadline, klass,
                            fp=fp)
        self.metrics.count("received")
        self._dispatch(req)         # sheds raise out of submit here
        return req.outer

    def generate(self, prompt, max_new_tokens, deadline_ms=None,
                 timeout=None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    # -- routing -------------------------------------------------------
    def _affinity_key(self, prompt):
        """The request's routing identity: its leading
        `affinity_blocks` x `affinity_block` tokens, floored to a
        block boundary (the paged pool shares whole blocks, so only
        whole blocks are placement-worthy). A prompt shorter than one
        block is its own key — short prompts still route stably."""
        bs = self.affinity_block
        n = min(len(prompt), self.affinity_blocks * bs)
        if n >= bs:
            n -= n % bs
        return tuple(int(t) for t in prompt[:n])

    def _pick(self, tried=(), key=None):
        with self._lock:
            cands = [r for r in self._replicas.values()
                     if r.state in (HEALTHY, DEGRADED)
                     and r.name not in tried and r.server.alive]
            if not cands:
                return None
            if self._policy == "round_robin":
                rec = cands[self._rr % len(cands)]
                self._rr += 1
                return rec
            least = min(cands, key=lambda r: (r.state != HEALTHY,
                                              r.inflight, r.seq))
            if self._policy == "affinity" and key is not None:
                home = self._pick_affine(cands, tried, key)
                if home is None or home is least:
                    # the affine replica IS the least-backlog one (or
                    # the ring routed around every candidate): sticky
                    # and cheap at once
                    self.metrics.count("routed_affinity")
                    return home if home is not None else least
                floor = min(r.inflight for r in cands)
                if home.inflight > self.spill_factor * floor \
                        + self.spill_slack:
                    # load-aware spill: stickiness is a goodput
                    # preference, never a hotspot — fall to the
                    # least-backlog survivor and count it
                    self.metrics.count("routed_spill")
                    return least
                self.metrics.count("routed_affinity")
                return home
            # least backlog; healthy beats degraded; spawn order ties
            return least

    def _pick_affine(self, cands, tried, key):
        """Ring owner of `key` among routable candidates (callers hold
        `self._lock`). The ring is (re)built only when the ALIVE
        roster changes — its stability across unrelated churn is the
        point (~1/N keys remap per replica added/removed)."""
        names = tuple(r.name for r in self._replicas.values()
                      if r.state in (HEALTHY, DEGRADED)
                      and r.server.alive)
        if names != self._ring_names:
            self._ring = _build_ring(names)
            self._ring_names = names
        routable = {r.name for r in cands}
        owner = _ring_lookup(
            self._ring, _ring_hash(key),
            exclude=frozenset(tried) | (set(names) - routable))
        if owner is None:
            return None
        return self._replicas.get(owner)

    def _dispatch(self, req):
        """Route `req` to a replica. Raises on request-level sheds and
        on a fleet with no routable replica; replica death between
        choice and submit retries the next survivor."""
        tried = set()
        last = None
        if self._policy == "affinity" and req.akey is None:
            req.akey = self._affinity_key(req.prompt)
        while True:
            rec = self._pick(tried, key=req.akey)
            if rec is None:
                raise last if last is not None else ReplicaDeadError(
                    "no alive replicas to route to")
            dl_ms = None
            if req.deadline is not None:
                left = (req.deadline - time.monotonic()) * 1e3
                if left <= 0:
                    raise DeadlineExceededError(
                        "deadline expired before the fleet could "
                        "place the request")
                dl_ms = left
            try:
                inner = rec.server.submit(req.prompt, req.max_new,
                                          deadline_ms=dl_ms,
                                          klass=req.klass)
            except (ServerClosedError, ReplicaDeadError) as e:
                # died between choice and submit: fail it loudly, move on
                self._crash(rec.name, reason=str(e))
                tried.add(rec.name)
                last = e
                continue
            self._register(rec, req, inner)
            if self._policy == "affinity" and req.akey:
                self._maybe_pull(rec, req.akey)
            if self._kill_hook is not None:
                # the poison chaos seam: a truthy hook verdict models
                # a decode that deterministically kills its replica —
                # the crash sweep below fails this request over (or
                # quarantines it on its second kill)
                try:
                    poisoned = bool(self._kill_hook(req.prompt,
                                                    rec.name))
                except Exception:   # noqa: BLE001 — chaos stays chaos
                    log.exception("kill hook raised; ignoring")
                    poisoned = False
                if poisoned:
                    self._crash(rec.name,
                                reason="poison decode killed replica")
            return

    def _register(self, rec, req, inner):
        with self._lock:
            req.replica = rec.name
            self._live[inner] = req
            rec.inflight += 1
        inner.add_done_callback(self._on_inner_done)

    # -- fleet prefix tier ---------------------------------------------
    def _maybe_pull(self, rec, key):
        """Schedule an async prefix pull for `key` into `rec` when the
        manager believes `rec` is cold on it and a peer is warm —
        spilled/remapped traffic adopts the peer's blocks instead of
        recomputing them. OFF the dispatch hot path: this method only
        consults host-side sets and (at most) starts a daemon thread —
        the no-pull affinity path stays at ZERO added device
        dispatches per token (the fleet A/B pin)."""
        with self._lock:
            if key in rec.keys_seen:
                rec.keys_seen.move_to_end(key)  # LRU touch
                return
            src = None
            if self.prefix_pull and self._pull_budget_left > 0 \
                    and (rec.name, key) not in self._pulls_inflight:
                src = self._pull_source(rec, key)
            # believed warm from here on: the request just routed here
            # will prefill (or adopt) the chain itself
            rec.keys_seen[key] = True
            while len(rec.keys_seen) > self._keys_seen_cap:
                rec.keys_seen.popitem(last=False)
            if src is None:
                return
            self._pulls_inflight.add((rec.name, key))
            budget = self._pull_budget_left
        t = threading.Thread(target=self._do_pull,
                             args=(src, rec.name, key, budget),
                             daemon=True, name=f"prefix-pull-{rec.name}")
        t.start()

    def _pull_source(self, rec, key):
        """Locked helper: the first alive peer the manager believes
        warm on `key` that speaks the pull protocol, or None."""
        for peer in self._replicas.values():
            if peer is not rec and key in peer.keys_seen \
                    and peer.state in (HEALTHY, DEGRADED) \
                    and peer.server.alive \
                    and getattr(peer.server, "prefix_export",
                                None) is not None:
                return peer.name
        return None

    def prefetch(self, prompt):
        """Synchronously re-warm `prompt`'s affinity key on its
        current ring owner by pulling a warm peer's resident blocks —
        the scale-up companion: after the ring remaps keys onto a
        freshly spawned replica, prefetch moves the cached prefix
        there AHEAD of traffic. (The dispatch-time pull exists too,
        but it races the triggering request's own prefill and concedes
        when local compute wins — correct either way; prefetch is for
        warming before the traffic arrives.) Spends the same fleet
        pull budget and counts through the same `prefix_pull_*`
        counters. Returns blocks adopted (0 when the owner is already
        believed warm, no warm peer exists, the budget is spent, or
        the pull was refused — refusals count at the adopting
        replica)."""
        key = self._affinity_key(tuple(int(t) for t in prompt))
        if not key:
            return 0
        with self._lock:
            cands = [r for r in self._replicas.values()
                     if r.state in (HEALTHY, DEGRADED)
                     and r.server.alive]
            if not cands:
                return 0
            dst = self._pick_affine(cands, (), key)
            if dst is None or key in dst.keys_seen:
                return 0
            if not self.prefix_pull or self._pull_budget_left <= 0 \
                    or (dst.name, key) in self._pulls_inflight:
                return 0
            src = self._pull_source(dst, key)
            if src is None:
                return 0
            dst.keys_seen[key] = True
            while len(dst.keys_seen) > self._keys_seen_cap:
                dst.keys_seen.popitem(last=False)
            self._pulls_inflight.add((dst.name, key))
            budget = self._pull_budget_left
        return self._do_pull(src, dst.name, key, budget)

    def _do_pull(self, src_name, dst_name, key, budget):
        """One pull, source -> destination, on its own daemon thread
        (both ends service it at their serve loops' iteration
        boundaries under their own bytes budgets). Failures are
        logged, never raised: the tier is an optimization — the worst
        outcome of a failed pull is the cold compute that would have
        happened anyway. Version refusals are counted by the ADOPTING
        replica (`prefix_pull_refused`), where the tag check runs.
        Returns blocks adopted (0 on any miss/refusal/failure)."""
        try:
            with self._lock:
                src = self._replicas.get(src_name)
                dst = self._replicas.get(dst_name)
            if src is None or dst is None or not src.server.alive \
                    or not dst.server.alive:
                return 0
            art = src.server.prefix_export(key, max_bytes=budget)
            if art is None:
                return 0
            adopt = getattr(dst.server, "prefix_adopt", None)
            if adopt is None:
                return 0
            n = adopt(art)
            with self._lock:
                self._pull_budget_left = max(
                    0, self._pull_budget_left - art.nbytes)
            return int(n or 0)
        except KVStateVersionError:
            return 0    # refusal counted at the adopting replica;
            #             the request decodes cold — correct, just slower
        except Exception:   # noqa: BLE001 — the tier must never raise
            log.debug("prefix pull %s -> %s failed", src_name,
                      dst_name, exc_info=True)
            return 0
        finally:
            with self._lock:
                self._pulls_inflight.discard((dst_name, key))

    def _on_inner_done(self, fut):
        with self._lock:
            req = self._live.pop(fut, None)
            if req is not None:
                rec = self._replicas.get(req.replica)
                if rec is not None:
                    rec.inflight = max(0, rec.inflight - 1)
        if req is None:
            return      # handed off (drain) or already accounted
        if fut.cancelled():
            req.outer.cancel()
            return
        # ONE classification table (_settle_handoff) for this live
        # path and the drain/crash handoff paths: result or a
        # request-level PROPAGATE verdict settles the outer future;
        # anything else is infrastructure — failover
        if not self._settle_handoff(fut, req):
            self._failover(req, fut.exception())

    def _failover(self, req, exc, blame=True):
        """Resubmit a request whose replica failed underneath it:
        prompt replay on a survivor (deterministic greedy decode ==
        the uninterrupted stream), bounded by the retry policy; out of
        budget / out of survivors / stopped manager fails the outer
        future LOUDLY with the original error. Before replaying, two
        containment gates: a request aboard its second distinct
        SPONTANEOUS replica death is the probable KILLER — quarantined,
        never replayed (`blame=False` excludes operator-initiated
        kills: the operator chose that victim, the request did not) —
        and a replay the fleet-wide retry budget refuses fails loudly
        instead of amplifying load."""
        if blame and isinstance(exc, ReplicaDeadError) \
                and req.replica is not None:
            req.deaths.add(req.replica)
            if len(req.deaths) >= _QUARANTINE_DEATHS:
                self._quarantine_req(req, exc)
                return
        req.attempts += 1
        if not self._running or req.attempts > self._retry.max_retries:
            if _fail_future(req.outer, exc):
                self.metrics.count("failed")
            return
        if not self._retry.grant_retry():
            self.metrics.count("retry_budget_exhausted")
            if _fail_future(req.outer, RetryBudgetExhaustedError(
                    f"fleet retry budget exhausted; not replaying "
                    f"after {type(exc).__name__}: {exc}")):
                self.metrics.count("failed")
            return
        d = self._retry.delay(req.attempts - 1)
        if d:
            # NEVER sleep here: this runs inside the inner future's
            # done-callback — on the dying replica's serve/kill
            # thread, where stacked backoffs would serially delay
            # every other victim's failure delivery (and kill()'s
            # join). A daemon timer pays the backoff off-thread.
            t = threading.Timer(
                d, self._resubmit,
                kwargs={"count_failover": True, "cause": exc},
                args=(req,))
            t.daemon = True
            t.start()
            return
        self._resubmit(req, count_failover=True, cause=exc)

    def _settle_handoff(self, fut, req):
        """THE verdict table, shared by the live done-callback and the
        drain/crash handoff paths: a resolved inner future's result —
        or its request-level PROPAGATE verdict (a deadline/overload/
        screen verdict must never be silently retried into success) —
        settles the outer future here. Returns True when settled
        (False: unresolved or an infrastructure error — the caller
        fails over / resubmits)."""
        if not fut.done() or fut.cancelled():
            return False
        exc = fut.exception()
        if exc is None:
            if _resolve_future(req.outer, fut.result()):
                self.metrics.count("completed")
                budget = self._retry.budget
                if budget is not None:
                    # successes are what pay for retries (SRE retry-
                    # budget discipline): refill a fraction per
                    # completion
                    budget.on_success()
            return True
        if isinstance(exc, self._PROPAGATE):
            if _fail_future(req.outer, exc):
                self.metrics.count("failed")
            return True
        return False

    def _quarantine_req(self, req, exc):
        """Convict one in-flight request as the poison pill: journal
        the fingerprint (a recovered manager keeps shedding it), add
        it to the bounded quarantine set, and fail the outer future
        with the typed verdict — this request is NEVER replayed."""
        fp = req.fp or _fingerprint(req.prompt, self._params_version)
        with self._lock:
            self._quarantine[fp] = True
            while len(self._quarantine) > self._quarantine_cap:
                self._quarantine.popitem(last=False)
        self.metrics.count("requests_quarantined")
        self._journal_append("quarantine", fingerprint=fp,
                             deaths=sorted(req.deaths))
        log.warning("request quarantined after %d replica deaths "
                    "(%s): fingerprint %s", len(req.deaths),
                    ", ".join(sorted(req.deaths)), fp[:12])
        if _fail_future(req.outer, PoisonPillError(
                f"request aboard {len(req.deaths)} replica deaths "
                f"({', '.join(sorted(req.deaths))}); fingerprint "
                f"{fp[:12]} quarantined")):
            self.metrics.count("failed")

    def _resubmit(self, req, count_failover=False, cause=None):
        if req.deadline is not None and \
                time.monotonic() > req.deadline:
            if _fail_future(req.outer, DeadlineExceededError(
                    "deadline expired during failover")):
                self.metrics.count("failed")
            return
        try:
            self._dispatch(req)
        except BaseException as e:  # noqa: BLE001 — outer carries it
            if _fail_future(req.outer, e):
                self.metrics.count("failed")
            return
        if count_failover:
            self.metrics.count("failover_resubmitted")
            log.warning("request replayed on %s after %s: %s",
                        req.replica, type(cause).__name__, cause)

    # -- replica lifecycle ---------------------------------------------
    def _mint_name(self):
        """Fleet-unique instance id: NEVER reused, even after the
        replica dies — a freshly spawned replica must not alias a dead
        one's metrics series, trace process group, or request-id
        namespace (the federation-under-churn pin)."""
        return f"{self._name_prefix}{next(self._name_ids)}"

    def _spawn(self):
        if not self._running:
            # a control tick racing stop() must not start a replica
            # nobody will ever stop (stop()'s final sweep catches the
            # narrower in-flight-spawn window)
            raise ServerClosedError("fleet manager is not running")
        name = self._mint_name()
        srv = self._factory(name)
        if not srv._running:
            srv.start()
        if hasattr(srv, "configure_wire"):
            # a REMOTE replica (serving/wire.py): bind the manager's
            # wire config — its metrics as the wire-counter sink
            # (wire_reconnects/wire_retries land on the fleet
            # control-plane snapshot), its retry policy, its
            # heartbeat-timeout reap threshold, and (when journaling)
            # this generation's epoch for stale-manager fencing
            srv.configure_wire(heartbeat_timeout=self.heartbeat_timeout,
                               retry_policy=self._retry,
                               counters=self.metrics,
                               epoch=self.epoch or None)
        if self._params is not None:
            try:
                same = srv.current_params()[0] is self._params[0]
            except NotImplementedError:
                same = False    # remote: no params pull — always ship
            if not same:
                # the factory builds the ORIGINAL params; a rolled-
                # forward fleet hands every new replica the current ones
                srv.swap(_ParamsView(*self._params))
        if self._warmup is not None:
            self._warmup(srv)
        with self._lock:
            orphaned = not self._running
            if not orphaned:
                rec = _Replica(name, srv, next(self._seq),
                               born=time.monotonic())
                self._replicas[name] = rec
                if self._breaker == BREAKER_HALF_OPEN \
                        and self._probe_name is None:
                    # this spawn IS the half-open probe: the breaker
                    # closes only if it survives infant_mortality_s
                    self._probe_name = name
        if orphaned:
            # stop() raced the slow factory/warmup above and its sweep
            # never saw this name: tear the orphan down HERE (outside
            # the lock — stop joins the serve thread) instead of
            # leaking a started serve thread nobody owns
            srv.stop(drain=False, timeout=10.0)
            raise ServerClosedError("fleet manager stopped during spawn")
        self.metrics.count("replica_spawned")
        # wire identity rides the spawn record (remote replicas carry
        # host/port/pid/start_time off their HELLO; in-process ones
        # journal None — recovery re-adopts only what it can re-dial)
        self._journal_append(
            "spawn", name=name, seq=rec.seq,
            host=getattr(srv, "_host", None),
            port=getattr(srv, "_port", None),
            pid=getattr(srv, "pid", None),
            start_time=getattr(srv, "start_time", None))
        log.info("replica %s spawned (%d alive)", name, self.n_alive())
        return name

    # -- spawn circuit breaker -----------------------------------------
    @property
    def breaker_state(self):
        """closed / open / half_open (the `breaker_state` gauge is the
        numeric twin: 0 / 1 / 0.5)."""
        with self._lock:
            return self._breaker

    def _breaker_strike(self, name=None):
        """One spawn-path strike: a factory/warmup raise or an infant
        death. K consecutive strikes OPEN the breaker; a failed
        half-open probe re-opens it with DOUBLED backoff."""
        now = time.monotonic()
        opened = False
        with self._lock:
            self._strikes += 1
            self._last_strike = now
            if self._breaker == BREAKER_HALF_OPEN and \
                    (name is None or name == self._probe_name
                     or self._probe_name is None):
                self._breaker = BREAKER_OPEN
                self._probe_name = None
                self._breaker_backoff = min(
                    self._breaker_max_backoff,
                    self._breaker_backoff * 2.0)
                self._breaker_until = now + self._breaker_backoff
                opened = True
            elif self._breaker == BREAKER_CLOSED and \
                    self._strikes >= self.breaker_strikes:
                self._breaker = BREAKER_OPEN
                self._breaker_until = now + self._breaker_backoff
                opened = True
            backoff = self._breaker_backoff
            strikes = self._strikes
        if opened:
            self.metrics.count("breaker_open_total")
            self.metrics.record_breaker_state(
                _BREAKER_GAUGE[BREAKER_OPEN])
            self._journal_append("breaker", state=BREAKER_OPEN,
                                 strikes=strikes, backoff_s=backoff)
            log.warning("spawn circuit breaker OPEN after %d strikes "
                        "(next probe in %.2fs); fleet degraded",
                        strikes, backoff)

    def _spawn_allowed(self):
        """The breaker gate every backfill/autoscale spawn passes:
        True while CLOSED; while OPEN, True exactly once per elapsed
        backoff window (that spawn becomes the half-open probe); False
        while a probe is pending or the backoff hasn't elapsed."""
        probing = False
        with self._lock:
            if self._breaker == BREAKER_CLOSED:
                return True
            if self._breaker == BREAKER_OPEN and \
                    time.monotonic() >= self._breaker_until:
                self._breaker = BREAKER_HALF_OPEN
                probing = True
        if probing:
            self.metrics.record_breaker_state(
                _BREAKER_GAUGE[BREAKER_HALF_OPEN])
            log.info("spawn breaker half-open: probing with one spawn")
            return True
        return False

    def _spawn_guarded(self):
        """Backfill/probe spawn with strike accounting: a raising
        factory (the spawn_fail chaos action) is a breaker STRIKE, not
        an unhandled control-loop error. Returns the name, or None on
        a strike. A stopping manager's refusal propagates — that is
        lifecycle, not a spawn-path failure."""
        try:
            return self._spawn()
        except ServerClosedError:
            raise
        except Exception:   # noqa: BLE001 — the strike IS the handling
            log.exception("spawn failed (breaker strike)")
            self._breaker_strike()
            return None

    def _breaker_probe_check(self):
        """Per-tick breaker bookkeeping: close the breaker when the
        half-open probe replica survives `infant_mortality_s`, and
        while CLOSED, reset the strike counter when any spawn born
        AFTER the last strike survives infancy (strikes are
        CONSECUTIVE spawn failures, not lifetime ones)."""
        closed = False
        now = time.monotonic()
        with self._lock:
            if self._breaker == BREAKER_HALF_OPEN and self._probe_name:
                rec = self._replicas.get(self._probe_name)
                if rec is not None \
                        and rec.state in (HEALTHY, DEGRADED) \
                        and rec.server.alive \
                        and rec.born is not None \
                        and now - rec.born >= self.infant_mortality_s:
                    self._breaker = BREAKER_CLOSED
                    self._strikes = 0
                    self._probe_name = None
                    self._breaker_backoff = self._breaker_backoff0
                    closed = True
            elif self._breaker == BREAKER_CLOSED and self._strikes:
                for rec in self._replicas.values():
                    if rec.born is not None \
                            and rec.born > self._last_strike \
                            and rec.state in (HEALTHY, DEGRADED) \
                            and now - rec.born \
                            >= self.infant_mortality_s:
                        self._strikes = 0
                        break
        if closed:
            self.metrics.record_breaker_state(
                _BREAKER_GAUGE[BREAKER_CLOSED])
            self._journal_append("breaker", state=BREAKER_CLOSED,
                                 strikes=0,
                                 backoff_s=self._breaker_backoff0)
            log.info("spawn circuit breaker CLOSED: probe survived "
                     "infancy")

    def _tombstone_counters(self, rec):
        """Counters-only snapshot of a departing replica: federated
        counters stay MONOTONE after the instance stops existing,
        while its stale gauges/summaries (capacity, occupancy) drop
        out of the live read-outs the detector consumes. FETCH-ONLY —
        and always called OUTSIDE `self._lock`: a REMOTE replica's
        `kind_snapshot()` is a wire round-trip (serving/wire.py
        `_fetch_snapshot`, seconds on a wedged wire), and holding the
        manager lock through it would stall every router/probe/
        federation path on one dead replica's socket (the graftlint
        lock-discipline finding this split fixed)."""
        try:
            snap = rec.server.metrics.kind_snapshot()
        except Exception:           # noqa: BLE001 — dead is dead
            snap = {}
        return {k: v for k, v in snap.items()
                if v.get("kind") == "counter"}

    def _install_tombstone(self, rec, counters):
        """Write half of the tombstone (the `_tombstones` map is only
        ever touched under the lock — a reader iterating it must
        never race a bare-dict write from a crash path)."""
        with self._lock:
            self._tombstones[rec.name] = counters

    def _crash(self, name, reason="injected fault", convict=True):
        """Replica death: fail it loudly, tombstone its counters, and
        resubmit its in-flight requests to survivors via prompt
        replay. Idempotent. `convict=False` marks an ADMINISTRATIVE
        death (operator kill, canary rollback): requests aboard it do
        not accrue a poison-pill strike — only spontaneous deaths are
        evidence a request's own decode is the killer."""
        with self._lock:
            rec = self._replicas.get(name)
        if rec is None:
            return
        # counters fetched BEFORE the removal and OUTSIDE the lock:
        # the replica stays visible in `_replicas` while the (possibly
        # wire-crossing) snapshot runs, so a concurrent fleet_view()
        # still federates it live — never in neither map, which would
        # read as every counter dipping by its whole history (a fake
        # counter reset to the detector)
        counters = self._tombstone_counters(rec)
        with self._lock:
            if self._replicas.get(name) is not rec:
                return              # raced another crash/drain
            del self._replicas[name]
            # tombstone installed in the SAME critical section as the
            # removal: no reader window between the two maps
            self._tombstones[name] = counters
            doomed = []
            for fut, req in list(self._live.items()):
                if req.replica == name:
                    del self._live[fut]
                    doomed.append((fut, req))
        rec.state = DEAD
        self.metrics.count("replica_dead")
        self._journal_append("replica_dead", name=name, reason=reason)
        if convict and rec.born is not None and \
                time.monotonic() - rec.born < self.infant_mortality_s:
            # died within infancy of its own spawn: a spawn-path
            # failure (bad factory/params/config), not a serving one —
            # strike the breaker. Administrative kills don't strike:
            # an operator putting down a young replica says nothing
            # about the factory
            self.metrics.count("infant_deaths")
            self._breaker_strike(name)
        rec.server.kill()           # fails remaining futures loudly
        # refresh with the final post-kill values (counters only grow
        # — and a remote's snapshot falls back to its last good cache
        # — so the refresh keeps monotonicity)
        self._install_tombstone(rec, self._tombstone_counters(rec))
        log.warning("replica %s dead (%s); %d in-flight requests "
                    "failing over", name, reason, len(doomed))
        for fut, req in doomed:
            if self._settle_handoff(fut, req):
                # finished (or reached a PROPAGATE verdict) just
                # before the crash landed: deliver THAT outcome
                continue
            # ONE failover implementation (budget, accounting, pacing)
            # for both arrival paths — here and the done-callback
            self._failover(req,
                           ReplicaDeadError(f"replica {name} died"),
                           blame=convict)

    def kill_replica(self, name):
        """Operator/chaos verb: crash `name` now (the same path the
        fleet.replica sever action takes). An operator kill is
        administrative — requests aboard it fail over without accruing
        a poison-pill strike."""
        self._crash(name, reason="killed by operator", convict=False)

    def scale_up(self):
        """Spawn one replica (the scale_up actuation; also the
        min_replicas backfill). Returns the new name."""
        return self._spawn()

    def scale_down(self, name=None, timeout=60.0):
        """Gracefully remove one replica: drain(migrate) its live
        decode-phase requests onto survivors (bit-identical resumed
        streams), replay its queued/prefilling requests, stop it.
        Default victim: fewest in-flight requests, newest spawn on
        ties (symmetric with scale_up). Refuses to go below ONE alive
        replica — the autoscale caller enforces min_replicas; this
        verb only keeps the fleet routable."""
        with self._lock:
            alive = [r for r in self._replicas.values()
                     if r.state in (HEALTHY, DEGRADED)]
            if len(alive) <= 1:
                raise ValueError("refusing to drain the last alive "
                                 "replica")
            if name is None:
                rec = min(alive, key=lambda r: (r.inflight, -r.seq))
            else:
                rec = self._replicas[name]
                if rec.state not in (HEALTHY, DEGRADED):
                    raise ValueError(f"replica {name} is {rec.state}")
            rec.state = DRAINING
            handoff = {}
            for fut, req in list(self._live.items()):
                if req.replica == rec.name:
                    del self._live[fut]
                    handoff[fut] = req
            rec.inflight = 0
        # intent BEFORE action (WAL discipline): a successor must know
        # this replica was being emptied — a drain_begin without its
        # replica_drained marks the replica non-re-adoptable
        self._journal_append("drain_begin", name=rec.name)
        try:
            migrated, replayed = rec.server.drain(timeout=timeout)
        except BaseException as e:  # noqa: BLE001 — degrade to crash
            log.exception("drain of %s failed; treating as crash",
                          rec.name)
            # fetch outside the lock, install atomically with the
            # removal (the _crash rule — see _tombstone_counters); a
            # concurrent _crash that already removed + killed this
            # replica OWNS the tombstone: overwriting its final
            # post-kill counters with this path's (possibly stale)
            # fetch would read as a counter dip to the detector
            counters = self._tombstone_counters(rec)
            with self._lock:
                raced = self._replicas.get(rec.name) is not rec
                if not raced:
                    del self._replicas[rec.name]
                    self._tombstones[rec.name] = counters
            rec.state = DEAD
            if not raced:
                self.metrics.count("replica_dead")
                self._journal_append("replica_dead", name=rec.name,
                                     reason="drain failed")
                rec.server.kill()
                self._install_tombstone(    # refresh: final values
                    rec, self._tombstone_counters(rec))
            for fut, req in handoff.items():
                # same settle-first rule as every handoff path: a
                # result or PROPAGATE verdict that landed before the
                # drain wedged must not be replayed
                if not self._settle_handoff(fut, req):
                    self._resubmit(req, count_failover=True, cause=e)
            return rec.name
        for fut, art in migrated:
            req = handoff.pop(fut, None)
            if req is not None:
                self._repoint_migrated(req, art)
        for fut, spec in replayed:
            req = handoff.pop(fut, None)
            if req is not None:
                self._resubmit(req)
        for fut, req in handoff.items():
            # completed — or already holding a PROPAGATE verdict —
            # before the drain swept it: deliver that outcome; only
            # infrastructure leftovers replay
            if not self._settle_handoff(fut, req):
                self._resubmit(req)
        # the drained replica is stopped: its snapshot is a local (or
        # stale-cached) read, but the fetch still runs outside the
        # lock — the _crash rule, uniformly; and like the crash-
        # degrade path above, a _crash that raced the drain already
        # owns the removal AND the (newer, post-kill) tombstone
        counters = self._tombstone_counters(rec)
        with self._lock:
            if self._replicas.get(rec.name) is rec:
                del self._replicas[rec.name]
                self._tombstones[rec.name] = counters
        rec.state = DEAD
        self.metrics.count("replica_drained")
        self._journal_append("replica_drained", name=rec.name)
        log.info("replica %s drained (%d migrated, %d replayed; %d "
                 "alive)", rec.name, len(migrated), len(replayed),
                 self.n_alive())
        return rec.name

    def _repoint_migrated(self, req, art):
        """Land a drained request's artifact on a survivor
        (`migrate_in` — the resumed stream is bit-identical); a
        version/layout refusal or an overloaded survivor degrades to
        prompt replay (correct bits either way — replay just pays the
        prompt compute again)."""
        dl_ms = None
        if req.deadline is not None:
            left = (req.deadline - time.monotonic()) * 1e3
            if left <= 0:
                if _fail_future(req.outer, DeadlineExceededError(
                        "deadline expired during drain migration")):
                    self.metrics.count("failed")
                return
            dl_ms = left
        tried = set()
        while True:
            rec = self._pick(tried)
            if rec is None or not rec.server.paged:
                self._resubmit(req)     # no migratable destination
                return
            try:
                inner = rec.server.migrate_in(art, deadline_ms=dl_ms)
            except (KVStateError, ValueError):
                # tag/layout mismatch (mid-rollout fleet) — the
                # destination REFUSED the migration: degrade to prompt
                # replay (correct bits either way), counted so a fleet
                # silently paying replay compute is visible
                self.metrics.count("migrate_refused")
                self._resubmit(req)
                return
            except ServerOverloadedError:
                self.metrics.count("migrate_refused")
                tried.add(rec.name)
                continue
            except (ServerClosedError, ReplicaDeadError) as e:
                self._crash(rec.name, reason=str(e))
                tried.add(rec.name)
                continue
            self._register(rec, req, inner)
            return

    # -- health + the closed autoscale loop ----------------------------
    def _probe_health(self):
        """Per-replica state machine: DEAD when the serve thread is
        gone (crash path — in-flight work fails over); DEGRADED while
        the replica's own shed rate (per tick, all causes) or failure
        counter is moving; back to HEALTHY on a quiet tick. Degraded
        replicas still serve (least-backlog prefers healthy ones) —
        the state is the canary gate's and the imbalance report's
        signal, not a kill switch."""
        with self._lock:
            recs = [r for r in self._replicas.values()
                    if r.state in (HEALTHY, DEGRADED)]
        for rec in recs:
            if not rec.server.alive:
                self._crash(rec.name, reason="serve thread died")
                continue
            m = rec.server.metrics
            sheds = sum(m.count_value(k) for k in SHED_KEYS)
            failed = m.count_value("failed")
            d_shed = sheds - rec.probe_sheds
            d_fail = failed - rec.probe_failed
            rec.probe_sheds, rec.probe_failed = sheds, failed
            if d_fail > 0 or d_shed >= self.degrade_shed_rate:
                if rec.state == HEALTHY:
                    rec.state = DEGRADED
                    self.metrics.count("replica_degraded")
            elif rec.state == DEGRADED:
                rec.state = HEALTHY

    def fleet_view(self):
        """FleetView over every ALIVE replica's kind_snapshot plus the
        counters-only tombstones of dead/drained ones (federated
        counters stay monotone across churn; stale gauges don't haunt
        the detector)."""
        fv = FleetView(signal=self.signal)
        with self._lock:
            recs = [r for r in self._replicas.values()
                    if r.state in (HEALTHY, DEGRADED, DRAINING)]
            tombs = list(self._tombstones.items())
        for rec in recs:
            fv.add(rec.name, rec.server.metrics)
        for name, snap in tombs:
            fv.add(name, snap)
        return fv

    def fleet_snapshot(self):
        """The federated snapshot with the manager's own control-plane
        counters overlaid (`fleet_replica_spawned`, ... — the manager
        is the one counting its own verbs)."""
        snap = self.fleet_view().snapshot()
        # fenced_ops stays FEDERATED: the replica hosting the fence is
        # the one counting refusals — a successor manager overlaying
        # its own (necessarily zero) count would erase the very events
        # the fence pin reads
        for key in ("replica_spawned", "replica_drained", "replica_dead",
                    "failover_resubmitted", "canary_rollbacks",
                    "wire_reconnects", "wire_retries",
                    "migrate_refused", "manager_epoch",
                    "replicas_adopted", "journal_records",
                    "requests_quarantined", "breaker_open_total",
                    "retry_budget_exhausted", "degraded_mode_ticks",
                    "infant_deaths", "routed_affinity", "routed_spill"):
            snap["fleet_" + key] = self.metrics.count_value(key)
        # prefix_pull_* stay FEDERATED (like fenced_ops): the ADOPTING
        # replica counts hits/bytes/refusals — the manager only
        # schedules pulls, it never adopts
        # the breaker gauge overlays LIVE manager state (a gauge, not a
        # counter — federation can't sum it; the manager owns it)
        snap["fleet_breaker_state"] = _BREAKER_GAUGE[self._breaker]
        snap["fleet_alive"] = self.n_alive()
        return snap

    def _utilization(self, snap, now):
        """Delivered tokens/s over the tick window divided by the
        fleet capacity estimate — the scale_down occupancy input. The
        per-replica occupancy reservoirs are ITERATION-weighted and no
        iterations run at idle, so their mean never decays on a quiet
        fleet; utilization does."""
        toks = snap.get("fleet_tokens_out") or 0
        rate = snap.get("fleet_service_rate_tokens_per_sec")
        last, self._last_tick = self._last_tick, (now, toks)
        if last is None or not rate:
            return snap.get("fleet_occupancy_mean")
        dt = now - last[0]
        if dt <= 0:
            return snap.get("fleet_occupancy_mean")
        return min(1.0, max(0.0, (toks - last[1]) / dt / rate))

    def control_tick(self):
        """ONE observation/actuation window of the closed loop: fire
        the crash-injection site per replica, probe health, backfill
        to min_replicas, federate a snapshot, consult the signal, and
        ACT on its decision (scale_up spawns; scale_down drains with
        live-request migration). After an action the signal resets —
        the next move is argued from the new fleet's own observations.
        Returns the tick record the sweep logs."""
        self._ticks += 1
        if self._injector is not None:
            with self._lock:
                names = [r.name for r in self._replicas.values()
                         if r.state in (HEALTHY, DEGRADED)]
            for n in names:
                self._injector.fire(
                    "fleet.replica",
                    on_sever=lambda name=n: self._crash(name))
        self._probe_health()
        self._breaker_probe_check()
        backfilled = 0
        while self._running and self.n_alive() < self.min_replicas:
            if not self._spawn_allowed():
                # breaker open (or probe pending): DEGRADED mode — no
                # tick-rate spawn crash-loop; serve on what's alive
                break
            if self._spawn_guarded() is not None:
                backfilled += 1
        if self._breaker != BREAKER_CLOSED:
            self.metrics.count("degraded_mode_ticks")
        if self._journal is not None and \
                self._journal_compact_bytes is not None:
            try:
                if self._journal.size() > self._journal_compact_bytes:
                    self._journal.compact(name_prefix=self._name_prefix)
            except Exception:   # noqa: BLE001 — the WAL is not the fleet
                log.exception("journal compaction failed")
        now = time.monotonic()
        snap = self.fleet_snapshot()
        util = self._utilization(snap, now)
        decision = None
        acted = None
        if self.signal is not None:
            decision = self.signal.observe(snap, occupancy=util)
            if self._rolling:
                pass        # a rollout owns the fleet shape right now
            elif decision == AutoscaleSignal.SCALE_UP \
                    and self._running \
                    and self.n_alive() < self.max_replicas \
                    and self._spawn_allowed():
                if self._spawn_guarded() is not None:
                    acted = "scale_up"
                    self.signal.reset()
            elif decision == AutoscaleSignal.SCALE_DOWN \
                    and self._running \
                    and self.n_alive() > self.min_replicas:
                self.scale_down()
                acted = "scale_down"
                self.signal.reset()
            if acted is not None:
                # the roster change itself is already journaled by
                # _spawn/scale_down; this records WHY (the autoscale
                # decision history a post-mortem replays)
                self._journal_append("autoscale", action=acted,
                                     tick=self._ticks)
        return {"tick": self._ticks, "decision": decision,
                "acted": acted, "backfilled": backfilled,
                "breaker": self._breaker,
                "n_replicas": self.n_alive(),
                "replicas": self.replicas,
                "states": self.states(), "utilization": util,
                "fleet_shed_predicted": snap.get("fleet_shed_predicted"),
                "fleet_tokens_out": snap.get("fleet_tokens_out")}

    # -- health-gated canary rollout -----------------------------------
    def rollout(self, new_lm, watch_ticks=2, traffic=None,
                tick_s=0.25, min_attainment=0.5, max_failures=0,
                shed_ratio=2.0, shed_allowance=4):
        """Hot-swap `new_lm`'s params across the fleet behind a health
        gate (module docstring). The NaN/Inf screen runs BEFORE any
        replica takes the params — a poisoned checkpoint rolls back
        with zero requests ever served under it. Then ONE canary
        replica swaps and serves live traffic for `watch_ticks`
        probation windows (`traffic()` is called per window when
        given — drive load there; otherwise the window is `tick_s` of
        wall clock); the gate trips on new failures/unhealthy outputs,
        SLO attainment under `min_attainment`, or the canary shedding
        more than `shed_ratio` x the survivors' mean (+
        `shed_allowance`). Tripped -> the canary swaps BACK (in-flight
        requests drain dual-version, zero dropped) and
        `canary_rollbacks` counts. Passed -> every other replica swaps
        (replica by replica, each its own dual-version drain) and
        future spawns inherit the new params. Returns the verdict
        record."""
        if not self._running:
            raise ServerClosedError("fleet manager is not running")
        if not _params_finite(new_lm):
            self.metrics.count("canary_rollbacks")
            log.warning("rollout refused: new params failed the "
                        "rowwise_finite screen")
            return {"status": "rolled_back", "reason": "nan_screen",
                    "canary": None}
        with self._lock:
            alive = [r for r in self._replicas.values()
                     if r.state == HEALTHY] or \
                    [r for r in self._replicas.values()
                     if r.state in (HEALTHY, DEGRADED)]
            if not alive:
                raise ReplicaDeadError("no alive replica to canary")
            canary = min(alive, key=lambda r: r.seq)
        old = canary.server.current_params()
        base = self._gate_counters(canary)
        base_peers = self._peer_sheds(exclude=canary.name)
        self._rolling = True
        # intent before action: a canary_begin with no matching
        # canary_rolled_* means the manager died mid-probation — the
        # recovery path rolls the orphaned canary back
        # deterministically (it alone holds unvetted params)
        self._journal_append("canary_begin", name=canary.name,
                             version=self._params_version + 1)
        try:
            canary.server.swap(new_lm)
            for _ in range(int(watch_ticks)):
                if traffic is not None:
                    traffic()
                else:
                    time.sleep(float(tick_s))
            cur = self._gate_counters(canary)
            delta = {k: cur[k] - base[k] for k in cur}
            peers = self._peer_sheds(exclude=canary.name)
            # deltas keyed BY NAME over the survivors present in both
            # samples: replica churn during probation (a background
            # control tick crashing/backfilling a peer) must never
            # pair one replica's before with another's after —
            # positional pairing would produce garbage (even negative)
            # baselines and flip the gate either way
            peer_delta = [peers[n] - base_peers[n]
                          for n in peers if n in base_peers] or [0]
            peer_mean = sum(peer_delta) / len(peer_delta)
            reason = None
            if delta["failed"] > int(max_failures):
                reason = f"failures: {delta['failed']}"
            elif delta["unhealthy_outputs"] > 0:
                reason = (f"unhealthy outputs: "
                          f"{delta['unhealthy_outputs']}")
            elif delta["slo_total"] > 0 and \
                    delta["slo_met"] / delta["slo_total"] \
                    < float(min_attainment):
                reason = (f"SLO attainment "
                          f"{delta['slo_met'] / delta['slo_total']:.2f}"
                          f" < {min_attainment}")
            elif delta["sheds"] > shed_allowance \
                    + shed_ratio * peer_mean:
                reason = (f"shed rate {delta['sheds']} vs survivors' "
                          f"mean {peer_mean:.1f}")
            if reason is not None:
                canary.server.swap(_ParamsView(*old))
                self.metrics.count("canary_rollbacks")
                self._journal_append("canary_rolled_back",
                                     name=canary.name, reason=reason)
                log.warning("canary %s rolled back: %s", canary.name,
                            reason)
                return {"status": "rolled_back", "reason": reason,
                        "canary": canary.name, "delta": delta}
            # gate passed: roll forward, replica by replica
            with self._lock:
                rest = [r for r in self._replicas.values()
                        if r.state in (HEALTHY, DEGRADED)
                        and r.name != canary.name]
            for rec in rest:
                rec.server.swap(new_lm)
            self._params = (new_lm.aux, new_lm.blocks)
            self._params_version += 1
            self._journal_append("canary_rolled_forward",
                                 name=canary.name,
                                 version=self._params_version)
            self._journal_append("params",
                                 version=self._params_version)
            log.info("rollout complete: canary %s + %d replicas on "
                     "new params", canary.name, len(rest))
            return {"status": "rolled_forward", "canary": canary.name,
                    "replicas": [canary.name] + [r.name for r in rest],
                    "delta": delta}
        finally:
            self._rolling = False

    def _gate_counters(self, rec):
        m = rec.server.metrics
        return {"failed": m.count_value("failed"),
                "unhealthy_outputs": m.count_value("unhealthy_outputs"),
                "slo_total": m.count_value("slo_total"),
                "slo_met": m.count_value("slo_met"),
                "sheds": sum(m.count_value(k) for k in SHED_KEYS)}

    def _peer_sheds(self, exclude):
        """name -> total sheds for every alive survivor (keyed so the
        rollout gate diffs per replica across its probation window)."""
        with self._lock:
            recs = [r for r in self._replicas.values()
                    if r.state in (HEALTHY, DEGRADED)
                    and r.name != exclude]
        return {r.name: sum(r.server.metrics.count_value(k)
                            for k in SHED_KEYS) for r in recs}
