"""Speculative decoding: K tokens per decode dispatch, bit-identical.

The serving decode loop (`serving/decode.py`) pays one device dispatch
per generated token per iteration — the exact cost model the fused-steps
work attacked for training, and on a remote-attached chip every dispatch
is a tunnel round-trip. Speculative decoding (Leviathan et al. 2023,
"Fast Inference from Transformers via Speculative Decoding") amortizes
it: a cheap DRAFT proposes K-1 candidate tokens, ONE K-wide verify
dispatch scores all of them, and the scheduler accepts the longest
prefix whose greedy argmax matches the draft plus one bonus token —
1..K tokens per dispatch. BOTH cache layouts run it: the fixed-slot
verify program (`models.zoo.transformer.make_slot_verify_fn`) and its
block-table twin (`make_paged_verify_fn` — same contract, writes
re-addressed through the block table under the paged chunk program's
[wfrom, wto) index gate), so `ContinuousDecodeServer(paged=True,
speculate=...)` — the production configuration — keeps the
dispatch-amortization win on the paged memory model.

Because the decode path is GREEDY, acceptance-by-exact-match makes the
emitted stream the verify program's OWN argmax chain by construction:
every accepted token IS that program's argmax at its position, so a
draft can only change how many dispatches the stream costs, never which
tokens it contains. Bit-identity with the plain 1-wide decode stream
then follows from argmax parity across dispatch widths — the same
measured cross-shape property the serving prefill/decode pin already
rests on (per-row gemm bits stable across M; a near-tie logit is the
theoretical exposure, same as bucket-padded prefill). That folds
speculation into the repo's determinism-pin culture (join == solo ==
`generate_batch`): a pure throughput lever, like continuous batching's
slot refill — pinned by tests/test_speculative.py across K ∈ {2, 4, 8},
both draft sources, solo/co-batched serving, and a mid-stream hot swap.

Two draft sources, both pluggable (the `DraftSource` protocol below):

  * `NGramDraft` — host-side prompt-lookup / self n-gram drafting: the
    request's OWN token history (prompt + accepted tokens) is the draft
    model; the longest recent n-gram matching the current suffix
    proposes its continuation. Zero extra model, zero extra dispatch —
    pure host work — and strong on repetitive text (code, greedy loops,
    retrieval-grounded prompts).
  * `ModelDraft` — a smaller `TransformerLM` with its own KV cache
    drafting K-1 tokens in K-1 cheap single-token dispatches. The draft
    cache tracks the ACCEPTED stream: rejected speculative rows are
    rolled back by pointer (dead rows, overwritten before attended —
    the same contract as the target's slot cache), so a divergence costs
    re-ingesting only the bonus token. Draft params are deliberately NOT
    version-pinned across target hot swaps: a stale draft lowers the
    acceptance rate, never correctness.

`Speculator` bundles a draft source with the verify width K — the object
`ContinuousDecodeServer(speculate=...)`, `TransformerLM.generate(
draft=...)` and `generate_batch(draft=...)` all accept.
"""
from __future__ import annotations

import logging

from .. import obs

log = logging.getLogger(__name__)


class DraftSource:
    """Protocol for draft-token providers. Keys identify independent
    request streams (the serving scheduler uses slot indices; generate()
    uses per-call sentinels); every method must be cheap host work or a
    small-model dispatch — never a blocking call into the target model.

    Lifecycle per stream: start(key, tokens) with the full context so far
    (prompt + first accepted token) -> repeated propose(key, k) /
    observe(key, accepted) pairs -> stop(key). A proposal may be SHORTER
    than k (including empty) when the source has nothing credible — the
    scheduler pads; padding costs acceptance, never correctness."""

    def start(self, key, tokens):
        raise NotImplementedError

    def observe(self, key, tokens):
        raise NotImplementedError

    def propose(self, key, k):
        raise NotImplementedError

    def stop(self, key):
        raise NotImplementedError


class NGramDraft(DraftSource):
    """Prompt-lookup / self n-gram drafting (host-side, zero dispatches).

    The draft "model" is the request's own token history: to propose,
    find the most recent PREVIOUS occurrence of the current suffix
    n-gram (longest n first, down to `min_match`) and propose the tokens
    that followed it. Greedy decode loves to repeat itself — and prompts
    that quote the text being continued (summarization, code edits,
    retrieval) repeat the prompt — which is exactly when this hits."""

    def __init__(self, n=3, min_match=1):
        if int(n) < int(min_match) or int(min_match) < 1:
            raise ValueError(f"need n >= min_match >= 1, got "
                             f"n={n} min_match={min_match}")
        self.n = int(n)
        self.min_match = int(min_match)
        self._hist = {}

    def start(self, key, tokens):
        self._hist[key] = [int(t) for t in tokens]

    def observe(self, key, tokens):
        self._hist[key].extend(int(t) for t in tokens)

    def propose(self, key, k):
        hist = self._hist[key]
        if k < 1:
            return []
        for g in range(min(self.n, len(hist) - 1), self.min_match - 1, -1):
            suffix = hist[-g:]
            # most recent prior occurrence wins (recency beats frequency
            # for continuation prediction); j is the index AFTER the match
            for j in range(len(hist) - 1, g - 1, -1):
                if hist[j - g:j] == suffix:
                    return hist[j:j + k]
            # fall through to a shorter suffix only when g never matched
        return []

    def stop(self, key):
        self._hist.pop(key, None)


class ModelDraft(DraftSource):
    """Draft tokens from a smaller `TransformerLM` with its own KV cache.

    Per stream, the draft keeps (cache, pos, pending, fed): `pos` is the
    committed cache frontier (rows < pos hold the ACCEPTED stream),
    `pending` are accepted tokens not yet ingested, `fed` are the
    speculative tokens fed past the frontier by the last propose().
    propose() ingests pending (one cheap dispatch each — the last
    ingest's logits seed the first proposal), then greedily decodes the
    remaining proposals. observe() rolls the frontier forward over the
    accepted prefix that matches what was fed (those speculative rows are
    already correct) and queues the rest — typically just the bonus token
    — so a round costs ~K draft dispatches, not a re-prefill.

    The draft model's max_len must cover the target's streams plus the
    speculative overhang (target max_len + k is always safe); proposals
    are truncated at the draft cache edge rather than overrunning it."""

    def __init__(self, lm):
        self.lm = lm
        # the CANONICAL single-token decode step — the draft shares
        # TransformerLM's own lazily-jitted program, so the step cannot
        # drift from generate(use_cache=True)'s and a self-draft
        # (ModelDraft(target)) compiles it exactly once
        self._step = lm._decode_step()
        self._max_len = int(lm.aux["pos"].shape[0])
        self._state = {}
        self.dispatch_count = 0     # device dispatches paid for drafting
        #                             (the scheduler folds these into
        #                             device_dispatches_per_token)

    def _feed(self, st, token):
        """One single-token draft dispatch at the stream frontier."""
        import jax.numpy as jnp
        logit, st["cache"] = self._step(
            self.lm.aux, self.lm.blocks, st["cache"],
            jnp.asarray(st["pos"], jnp.int32),
            jnp.asarray([int(token)], jnp.int32))
        st["pos"] += 1
        self.dispatch_count += 1
        return logit

    def start(self, key, tokens):
        from ..models.zoo.transformer import init_kv_cache
        self._state[key] = {
            "cache": init_kv_cache(len(self.lm.blocks), 1, self._max_len,
                                   self.lm.aux["tok"].shape[1],
                                   self.lm.n_heads,
                                   self.lm.aux["tok"].dtype),
            "pos": 0,
            "base": 0,
            "pending": [int(t) for t in tokens],
            "fed": [],
        }

    def observe(self, key, tokens):
        st = self._state[key]
        tokens = [int(t) for t in tokens]
        m = 0
        while m < min(len(tokens), len(st["fed"])) and \
                tokens[m] == st["fed"][m]:
            m += 1
        # keep the speculative rows the target accepted; roll back past
        # the divergence (dead rows, overwritten before attended)
        st["pos"] = st["base"] + m
        st["fed"] = []
        st["pending"].extend(tokens[m:])

    def propose(self, key, k):
        import numpy as np
        # one span per proposal round: a ModelDraft's K-1 dispatches are
        # real device work the timeline must show next to the verify
        # dispatch they amortize (an NGramDraft never appears here)
        with obs.TRACER.span("draft.propose", cat="serve", track="server",
                             k=int(k)):
            st = self._state[key]
            logit = None
            while st["pending"] and st["pos"] < self._max_len:
                logit = self._feed(st, st["pending"].pop(0))
            st["base"] = st["pos"]
            st["fed"] = []
            if logit is None or k < 1:
                # nothing newly ingested to seed from (or cache exhausted)
                return []
            out = []
            for i in range(int(k)):
                nt = int(np.asarray(logit).argmax())
                out.append(nt)
                if i < int(k) - 1:
                    if st["pos"] >= self._max_len:
                        break           # draft cache edge: truncate
                    logit = self._feed(st, nt)
                    st["fed"].append(nt)
            return out

    def stop(self, key):
        self._state.pop(key, None)


class Speculator:
    """Draft source + verify width K, the bundle the serving/scheduling
    layers accept. K is the WIDTH of the verify program: K-1 draft
    tokens in, 1..K tokens accepted per dispatch (matched prefix + one
    bonus). k=1 degenerates to plain decode through the verify program."""

    def __init__(self, draft, k=4):
        if not isinstance(draft, DraftSource):
            raise TypeError(f"draft must be a DraftSource, got "
                            f"{type(draft).__name__}")
        if int(k) < 1:
            raise ValueError(f"speculative width k must be >= 1, got {k}")
        self.draft = draft
        self.k = int(k)


def as_speculator(obj, k=4):
    """Normalize `speculate=`/`draft=` arguments: a Speculator passes
    through; a bare DraftSource is wrapped with width `k`."""
    if obj is None:
        return None
    if isinstance(obj, Speculator):
        return obj
    return Speculator(obj, k)
