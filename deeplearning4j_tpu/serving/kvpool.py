"""Paged KV-cache memory management: BlockPool + per-request block
tables + prefix reuse (vLLM PagedAttention, Kwon et al. SOSP'23).

The fixed-slot decode cache reserves `max_len` rows per slot, so
concurrent-stream count is bounded by WORST-CASE length, not actual
usage — four 160-row slots serve four streams even when each stream
touches 30 rows. Paging splits the cache into fixed-size BLOCKS in one
preallocated arena (`models.zoo.transformer.init_paged_kv_cache`) and
gives every request a block TABLE (logical block -> physical block);
the decode program gathers attention rows through the table
(`make_paged_decode_fn`), so a stream holds exactly the blocks its rows
occupy and admission is gated by FREE BLOCKS, not free slots. Slot
count becomes a pure scheduling width.

This module is the HOST half: pure-Python block accounting, zero jax
imports — allocation decisions can never add a device dispatch, and the
pool unit-tests without a device. The device half (arena layout, the
gather/scatter programs, the CoW block copy) lives in the zoo.

Three mechanisms:

* **Free-list allocation, refcounted blocks.** Blocks are free, CACHED
  (refcount 0 but contents still indexed for prefix reuse — evicted LRU
  on demand), or in use (refcount >= 1; > 1 means shared). A request's
  blocks — prompt AND decode rows — are reserved at `admit()` so a
  mid-decode append can never deadlock the pool: either a request
  admits with everything it will ever write, or it waits.
* **Prefix reuse.** Full prompt blocks are indexed by the TOKEN PREFIX
  they complete (exact tuple keys — a dict lookup, no hash-collision
  exposure) under a caller-supplied `tag`. A new request walks the
  index block by block and maps matched leading blocks to the one
  physical copy (refcount++): system prompts and few-shot templates —
  the dominant shape of real traffic — are stored once no matter how
  many streams carry them. Correctness rests on determinism the repo
  already pins: same tokens + SAME PARAMS => bit-identical k/v rows
  regardless of which request computed them (per-row bits independent
  of batch shape), so reading a neighbour's block IS reading your own.
  The "same params" half is why the tag exists: the decode server tags
  every admission with its param VERSION, so a request admitted after a
  hot swap can never match blocks whose k/v were computed under the old
  weights — cross-version reuse is structurally impossible, and stale
  versions' cached blocks simply age out of the LRU.
* **Copy-on-write.** A shorter prompt can also ride the FIRST PART of a
  longer prompt's final indexed block (the partial tail match). Such a
  sharer must not append into the shared block — its first generated
  row would clobber the owner's — so `admit()` reserves a spare and the
  scheduler calls `cow()` right before the first divergent append: the
  spare replaces the shared block in the sharer's table and the device
  copies the rows across (`make_block_copy_fn`). Prefill-only requests
  (max_new_tokens == 1) never append and share the partial block free
  of any copy.

`ContinuousDecodeServer(paged=True)` wires this to the device programs;
tests/test_paged.py pins the invariants (no leak after churn, refcount
consistency, CoW correctness, join == solo bit-identity).
"""
from __future__ import annotations

import collections

__all__ = ["BlockPool", "PagedAllocation"]


class PagedAllocation:
    """One request's block-table allocation.

    ids:         physical block ids in table order (logical block i of
                 the request lives at physical block ids[i]).
    shared_rows: leading prompt rows resident BEFORE this request's
                 prefill (the prefix-cache hit) — the prefill program
                 skips installing them.
    n_shared:    leading ids held by refcount only (never written by
                 this request while shared).
    cow:         None, or (logical_block_idx, spare_block_id): a lazy
                 copy-on-write the scheduler must materialize via
                 `BlockPool.cow()` before this request's first appended
                 row lands in that block.
    pending_index: (position, prefix-key) pairs to register in the
                 prefix index via `BlockPool.commit()` — called by the
                 scheduler ONLY after the prefill dispatch succeeded.
                 Registering at admit() would let a failed prefill
                 leave never-written blocks indexed, and a later
                 same-prompt request would "share" garbage rows.
    """

    __slots__ = ("ids", "shared_rows", "n_shared", "cow",
                 "pending_index")

    def __init__(self, ids, shared_rows, n_shared, cow, pending_index):
        self.ids = ids
        self.shared_rows = int(shared_rows)
        self.n_shared = int(n_shared)
        self.cow = cow
        self.pending_index = pending_index


class BlockPool:
    """Host-side block accounting for one paged KV arena."""

    def __init__(self, n_blocks, block_size, prefix_cache=True):
        self.capacity = int(n_blocks)
        self.block_size = int(block_size)
        if self.capacity < 1:
            raise ValueError(f"need n_blocks >= 1, got {n_blocks}")
        if self.block_size < 1:
            raise ValueError(f"need block_size >= 1, got {block_size}")
        self.prefix_cache = bool(prefix_cache)
        # low ids allocate first (pop from the end of a descending list):
        # deterministic placement for a deterministic test surface
        self._free = list(range(self.capacity - 1, -1, -1))
        self._ref = {}          # id -> refcount (>= 1: in use)
        # ref==0 blocks whose contents stay indexed: the prefix cache
        # proper, evicted LRU when the free list runs dry
        self._cached = collections.OrderedDict()    # id -> index key
        self._index = {}        # (tag, prefix token tuple) -> block id
        self._key_of = {}       # block id -> its index key
        self._children = {}     # parent prefix key -> {id: ext tuple}
        # blocks ADOPTED from a persistent prefix-cache artifact
        # (serving/kvstate.py) and still indexed: the decode server
        # counts a prefix match landing on one as `prefix_restore_hits`
        # — the restart-warm-start proof. Membership ends at _unindex
        # (an evicted-then-reallocated block is a fresh block).
        self.restored = set()

    # -- read-outs -----------------------------------------------------
    @property
    def blocks_in_use(self):
        """Blocks held by live requests (refcount >= 1)."""
        return self.capacity - len(self._free) - len(self._cached)

    @property
    def blocks_free(self):
        """Allocatable RIGHT NOW: the free list plus evictable cached."""
        return len(self._free) + len(self._cached)

    def blocks_needed(self, total_rows):
        """Table length for a request that will ever write `total_rows`
        KV rows (prompt + generated-but-one; the final emitted token is
        never written back)."""
        return max(1, -(-int(total_rows) // self.block_size))

    def writable_rows(self, alloc):
        """Row capacity of one allocation's reserved table — the
        EXCLUSIVE write bound (`wto`) the K-wide paged programs gate
        on. Rows between the request's last real row and this bound are
        the tail of its final reserved block: dead-writable overhang a
        speculative round or chunk padding may scribble on (the pointer
        never passes them, and the block is privately owned). Rows at
        or past this bound are OUTSIDE the reservation — an ungated
        write there would resolve through a zeroed block-table entry
        into block 0, i.e. someone else's memory — so the verify/chunk
        programs index-drop them."""
        return len(alloc.ids) * self.block_size

    # -- prefix matching ----------------------------------------------
    def match_prefix(self, prompt, tag=None):
        """(full_ids, rows_matched, partial_id): the longest run of
        indexed blocks whose contents equal `prompt`'s leading full
        blocks UNDER `tag`, plus at most one PARTIAL match — an indexed
        block whose first rows equal ALL remaining prompt tokens (a
        shorter prompt riding a longer one's final block). Blocks
        indexed under a different tag never match: the decode server
        tags by param version, so k/v computed under swapped-out
        weights are unreachable. Pure lookup: takes no references,
        mutates no state."""
        if not self.prefix_cache:
            return [], 0, None
        prompt = tuple(int(t) for t in prompt)
        bs = self.block_size
        ids, rows = [], 0
        while rows + bs <= len(prompt):
            bid = self._index.get((tag, prompt[:rows + bs]))
            if bid is None:
                break
            ids.append(bid)
            rows += bs
        partial = None
        rem = prompt[rows:]
        if rem and len(rem) < bs:
            for bid, ext in (self._children.get((tag, prompt[:rows]))
                             or {}).items():
                if ext[:len(rem)] == rem:
                    partial = bid
                    rows += len(rem)
                    break
        return ids, rows, partial

    # -- allocation ----------------------------------------------------
    def admit(self, prompt, total_rows, will_append=True, tag=None):
        """Build a block table for one request, or return None when the
        pool cannot currently supply the blocks (the admission gate:
        BLOCKED ON MEMORY, not on slots — the caller holds the request
        and retries as completions free blocks).

        `total_rows` is every KV row the request will EVER write
        (reserved up front — see module docstring); `will_append` False
        (a prefill-only request) skips the copy-on-write spare, letting
        it share a partial block with zero copies. `tag` namespaces the
        prefix index (the server passes the param version — see module
        docstring). On success, `commit()` registers the request's own
        full prompt blocks under the same tag."""
        prompt = tuple(int(t) for t in prompt)
        bs = self.block_size
        n_total = self.blocks_needed(total_rows)
        shared, shared_rows, partial = self.match_prefix(prompt, tag)
        use_partial = partial is not None
        n_fresh = n_total - len(shared) - (1 if use_partial else 0)
        if n_fresh < 0:
            # prompt-dominated tiny request: the match covers more
            # blocks than the table needs — trim the tail of the match
            drop = -n_fresh
            if use_partial:
                use_partial = False
                shared_rows = len(shared) * bs
                drop -= 1
            if drop:
                shared = shared[:-drop]
                shared_rows = len(shared) * bs
            n_fresh = n_total - len(shared) - (1 if use_partial else 0)
        need_cow = use_partial and will_append
        if need_cow and n_total + 1 > self.capacity:
            # a capacity-sized table PLUS its CoW spare can never be
            # satisfied, not even by an empty pool — forgo the partial
            # ride (prefill recomputes those rows) instead of parking
            # the request in the memory queue forever
            use_partial = False
            need_cow = False
            shared_rows = len(shared) * bs
            n_fresh = n_total - len(shared)
        need_new = n_fresh + (1 if need_cow else 0)
        revive = [b for b in shared + ([partial] if use_partial else [])
                  if b in self._cached]
        if need_new > len(self._free) + len(self._cached) - len(revive):
            return None
        for b in shared:
            self._take(b)
        if use_partial:
            self._take(partial)
        fresh = [self._alloc_raw() for _ in range(need_new)]
        for b in fresh:
            self._ref[b] = 1
        spare = fresh.pop() if need_cow else None
        ids = shared + ([partial] if use_partial else []) + fresh
        pending = []
        if self.prefix_cache:
            # this request's own full PROMPT blocks (positions the match
            # did not cover) become shareable — but only AFTER the
            # prefill actually writes them: commit() registers these,
            # called by the scheduler on prefill success. Generated-token
            # blocks are private and never indexed.
            pending = [(i, (tag, prompt[:(i + 1) * bs]))
                       for i in range(len(shared),
                                      min(len(prompt) // bs, n_total))]
        cow = (len(shared), spare) if spare is not None else None
        return PagedAllocation(ids, shared_rows,
                               len(shared) + (1 if use_partial else 0),
                               cow, pending)

    def commit(self, alloc):
        """Register `alloc`'s freshly-PREFILLED full prompt blocks in
        the prefix index. Call ONLY after the prefill dispatch
        succeeded — an admitted-but-never-filled block must never become
        matchable (a sharer would read garbage rows)."""
        for i, key in alloc.pending_index:
            if key not in self._index:
                self._register(alloc.ids[i], key)
        alloc.pending_index = []

    def cow(self, alloc):
        """Materialize a lazy copy-on-write: the spare reserved at
        admit() replaces the shared partial block in `alloc`'s table.
        Returns (src, dst) physical ids — the CALLER performs the device
        row copy (`make_block_copy_fn`) before its next append dispatch,
        whatever its width: the 1-wide decode step writes one frontier
        row into the shared block, and a K-wide VERIFY dispatch writes
        its whole [pos, pos+K) burst starting there — both must see the
        private copy first (the scheduler materializes any pending CoW
        before the first decode-phase dispatch, which covers both)."""
        idx, spare = alloc.cow
        src = alloc.ids[idx]
        alloc.ids = list(alloc.ids)
        alloc.ids[idx] = spare
        alloc.cow = None
        alloc.n_shared -= 1
        self._drop(src)
        return src, spare

    def cached_entries(self, tag=None):
        """(block id, prefix tokens) for every CACHED (refcount-0,
        still-indexed) block under `tag`, in LRU order — the saveable
        set the persistent prefix cache serializes
        (serving/kvstate.py). An accessor, so persistence reads the
        cached tier through the pool's API the same way restore writes
        it through `adopt()` — a representation change here cannot
        silently break the save path."""
        return [(bid, key[1]) for bid, key in self._cached.items()
                if key[0] == tag]

    def indexed_chain(self, key, tag=None):
        """Parent-first (block id, prefix tokens) chain of INDEXED
        blocks covering `key`'s leading full blocks under `tag` — the
        shippable set the fleet prefix tier exports (PREFIX_PULL).
        Unlike `cached_entries`, the chain is NOT restricted to the
        refcount-0 cached tier: a hot prefix is, by definition, held by
        live requests, and an indexed block's rows are immutable once
        committed (commit-after-prefill + the CoW discipline), so the
        exporter may extract them while they are still referenced.
        Pure lookup, like `match_prefix`: takes no references."""
        if not self.prefix_cache:
            return []
        key = tuple(int(t) for t in key)
        bs = self.block_size
        out, rows = [], 0
        while rows + bs <= len(key):
            prefix = key[:rows + bs]
            bid = self._index.get((tag, prefix))
            if bid is None:
                break
            out.append((bid, prefix))
            rows += bs
        return out

    def adopt(self, key):
        """Allocate a block for an EXTERNALLY-RESTORED prefix entry
        (serving/kvstate.py `PrefixCacheArtifact`): take a physical
        block, register `key` ((tag, prefix tokens)) in the index, and
        park it straight in the CACHED tier (refcount 0, LRU-evictable
        — exactly where `release` retires an indexed block). The CALLER
        installs the artifact's rows into the returned block id before
        any request can match it; the server does both under one
        restore call before serving starts, so a half-restored entry is
        never matchable. Returns None when the key is already indexed
        (nothing to adopt) or the FREE list is dry — adoption never
        evicts cached state (on a full pool that would recycle the
        blocks adoption itself just parked, churning the restore into
        a last-writer-wins shuffle): a too-small pool restores a
        prefix of the artifact, never fails the server."""
        if not self.prefix_cache or key in self._index:
            return None
        if not self._free:
            return None
        bid = self._free.pop()
        self._register(bid, key)
        self._cached[bid] = key
        self.restored.add(bid)
        return bid

    def release(self, alloc):
        """Return one request's blocks: refcount--, last reference
        retires an indexed block to the prefix cache (LRU-evictable) and
        frees a private one outright. An unmaterialized CoW spare is
        freed too."""
        for bid in alloc.ids:
            self._drop(bid)
        if alloc.cow is not None:
            self._drop(alloc.cow[1])
            alloc.cow = None
        alloc.ids = []
        alloc.pending_index = []    # uncommitted blocks stay unindexed

    # -- internals -----------------------------------------------------
    def _take(self, bid):
        if bid in self._cached:
            del self._cached[bid]
            self._ref[bid] = 1
        else:
            self._ref[bid] += 1

    def _drop(self, bid):
        self._ref[bid] -= 1
        if self._ref[bid] > 0:
            return
        del self._ref[bid]
        key = self._key_of.get(bid)
        if key is not None and self.prefix_cache:
            self._cached[bid] = key     # newest at the LRU tail
        else:
            self._free.append(bid)

    def _alloc_raw(self):
        if self._free:
            return self._free.pop()
        bid, key = self._cached.popitem(last=False)     # LRU evict
        self._unindex(bid, key)
        return bid

    @staticmethod
    def _parent_ext(key, bs):
        """key = (tag, prefix tokens): parent strips this block's bs
        tokens; ext is the stripped tail (the block's own contents)."""
        tag, prefix = key
        return (tag, prefix[:-bs]), prefix[-bs:]

    def _register(self, bid, key):
        self._index[key] = bid
        self._key_of[bid] = key
        parent, ext = self._parent_ext(key, self.block_size)
        self._children.setdefault(parent, {})[bid] = ext

    def _unindex(self, bid, key):
        del self._index[key]
        del self._key_of[bid]
        self.restored.discard(bid)
        parent, _ = self._parent_ext(key, self.block_size)
        kids = self._children.get(parent)
        if kids is not None:
            kids.pop(bid, None)
            if not kids:
                del self._children[parent]

    def check(self):
        """Internal-consistency invariants; raises AssertionError on a
        leak or a dangling reference (tests call this after churn)."""
        seen = (set(self._free) | set(self._cached) | set(self._ref))
        assert len(self._free) + len(self._cached) + len(self._ref) \
            == self.capacity, "block leaked or double-booked"
        assert seen == set(range(self.capacity)), "block ids corrupted"
        assert all(r >= 1 for r in self._ref.values()), \
            "zero refcount left in the in-use map"
        assert all(self._index.get(k) == b
                   for b, k in self._key_of.items()), \
            "index / key_of disagree"
        assert all(self._key_of.get(b) == k
                   for b, k in self._cached.items()), \
            "cached block lost its index key"
        assert self.restored <= set(self._key_of), \
            "restored-block marker outlived its index entry"
        return True
