"""Continuous-batching KV-cache decode scheduler (Orca, OSDI'22).

Static request batching decodes a gang of requests until the LAST one
finishes: a 5-token reply waits for the 200-token reply it shares a batch
with, and its slot emits padding the whole time. Iteration-level
("continuous") batching reschedules at TOKEN granularity instead — a
fixed-slot decode program (`models.zoo.transformer.make_slot_decode_fn`)
runs one token for every occupied slot per dispatch, and requests join or
leave slots BETWEEN dispatches. Prefill and decode are separated: a
joining request's prompt runs through a per-prompt-length-bucket prefill
program (`make_prefill_fn`) whose cache rows are scattered into the free
slot, then the request rides the shared decode program.

Determinism pin (tests/test_serving.py): a request's token stream is
bit-identical whether it decodes alone or joins a running batch — every
slot's row math touches only its own cache/pos/token rows, and inactive
slots' cache writes are gated. So continuous batching is a pure
throughput lever, not an accuracy trade.

Hot swap keeps MULTIPLE param versions live while draining (one per
undrained swap, typically two): slots keep the version they started with
(a compiled program takes params as arguments, so versions share ONE
executable), each iteration dispatches once per live version with the
active mask restricted to that version's slots, and new requests route
to the newest version immediately — zero admission stall, zero dropped
in-flight requests. Drained versions are released on request completion
AND on idle iterations, so repeated swaps never accumulate dead params.

Speculative decoding (`speculate=`, serving/speculate.py): the 1-token
step is replaced by a K-wide verify program (`make_slot_verify_fn`) —
each iteration drafts K-1 tokens per slot (host-side n-gram lookup or a
small draft model) and ONE dispatch accepts 1..K of them per slot.
Slots advance VARIABLE token counts per iteration (the per-slot
positions already support ragged advance), streams stay bit-identical
to plain greedy decode (the accepted tokens are the verify program's
own argmax chain by construction; cross-width argmax parity is pinned
by test — see speculate.py), and speculation composes with the
dual-version swap drain (verify runs under the slot's pinned version;
the draft needs no pinning — it can only cost acceptance).

Deadlines are enforced mid-decode, not just at admission: a slot whose
request outlives its latency budget is evicted between iterations
(future fails with DeadlineExceededError, shed counted, slot refilled
the same iteration).

Paged KV cache (`paged=True`, serving/kvpool.py + the zoo's
`make_paged_decode_fn` / `make_paged_prefill_fn`): the fixed-slot cache
reserves `max_len` rows per slot, so concurrency is bounded by
WORST-CASE length. Paged mode keeps one flat block arena instead; every
request holds a block table, admission is gated by FREE BLOCKS (a
request that cannot get its blocks waits in a memory queue — counted
`blocked_on_memory` — while slots are a pure scheduling width), and
prompt prefixes shared across requests (system prompts, few-shot
templates) map to ONE physical copy with copy-on-write before any
divergent append. Prefill is two programs — a pure prefill returning
k/v panels plus a small DONATED install scatter (mirroring the fixed
path; a fused install would copy the whole undonated arena); decode
stays one dispatch per iteration — paging adds ZERO device dispatches
per token (pinned by counter A/B in tests/test_paged.py), and the
join==solo determinism pin carries over unchanged. `paged=True`
COMPOSES with `speculate=`: the K-wide verify program has a
block-table twin (`make_paged_verify_fn` — writes at table-mapped
frontier rows under the same [wfrom, wto) index gate the paged chunk
program uses, gather attention over the slot's logical window), so
the production configuration keeps the dispatch-amortization win. A
speculative round consumes only blocks its reserve-at-admit table
already holds (no new allocation path), and a CoW-shared partial
block materializes before the FIRST verify dispatch — the K-wide
write starts at the frontier inside that block, so the 1-wide CoW
rule covers it unchanged.

Overload control (PR 9; serving/admission.py + the zoo's
`make_chunked_prefill_fn`) makes saturation a SURVIVABLE regime instead
of the goodput collapse PR 7 measured (past the knee: 2,515 -> 635
tok/s, TTFT p99 x30, queue_wait 72% of request time). Three levers:

* **Chunked prefill** (`chunked_prefill=C`): a joining request's prompt
  no longer runs as one monolithic prefill dispatch that stalls every
  co-resident stream for the whole prompt. The request is admitted into
  its slot in a PREFILL phase and advances C rows per scheduling
  iteration through a verify-shaped chunk program (fixed-slot and paged
  layouts), interleaved with everyone else's decode iterations — the
  head-of-line stall shrinks from O(prompt) to O(chunk), which is what
  the `sched_gap` phase in obs/decompose.py measures. The SIZING RULE
  (see _admit): only prompts longer than one chunk take this path — a
  short prompt already is a chunk-sized stall, and the one-shot bucket
  program runs it at [1, Pb] where the chunk program pays [slots, C].
  The chunked stream is BIT-IDENTICAL to the one-shot stream (the
  join==solo pin extended — tests/test_overload.py), and in paged mode
  chunking starts AFTER any resident shared prefix, so a prefix-cache
  hit now saves the prompt COMPUTE too (the partial-prefill seam PR 8
  left open), not just the memory.
* **Deadline-aware admission** (`admission=` an
  `admission.AdmissionController`, or True for defaults): a
  service-rate estimator over recent scheduling iterations (rolling
  median of iteration time + per-slot token rate — admission.py
  explains why those are the robust, occupancy-independent primitives)
  predicts, at ENQUEUE, when a request would complete behind the
  current backlog of work units; requests that cannot make their
  deadline are shed immediately as `shed_predicted` instead of eating
  queue slots and dying mid-decode. The estimator sheds LATE by
  construction (conservatism knob, cold warm-up guard) and
  SELF-CORRECTS systematic optimism: every prediction's signed error
  — completions exactly, evictions as a certain bound — feeds both
  the `admission_error_ms` histogram (observability) and the
  controller's bias loop.
* **Brownout policy** (`brownout=` an `admission.BrownoutPolicy`):
  accept/defer/shed per request CLASS (`submit(..., klass=)`) driven by
  queue depth and recent SLO attainment — deferred requests park in a
  side line served only when the primary queue is empty, so batch-class
  work yields to interactive work under pressure by POLICY, not queue
  accident. Deferred and memory-parked lines are both failed on
  fail-fast stop and both drain bounded by their remaining work on
  stop(drain=True) — expired deadlines shed at admission, so a
  saturated drain never decodes work nobody can use.
* **Durable KV state** (`serving/kvstate.py` + the zoo's
  `make_block_extract_fn`): a live request's KV block set can leave the
  arena as a host-side `RequestArtifact` (panel rows + token history +
  position + param-version tag) and come back bit-identically — ONE
  serialization primitive closing three production seams. (1)
  PREEMPTION (`preempt=True`, paged + brownout): when a request whose
  class outranks a live slot's (`BrownoutPolicy.may_preempt` — the
  accept/defer/shed verbs extended with preempt) is blocked on KV
  blocks, the victim slot is spilled to host (`preempted`,
  `spill_bytes`), its blocks go to the claimant, and the victim parks
  on a RESUME LINE served ahead of the queue as blocks free
  (`resumed`) — interactive TTFT is bounded at FULL BLOCK OCCUPANCY,
  which queue-depth admission structurally cannot do; the resume
  line's remaining work stays in the admission estimator's backlog
  (plus one re-install unit), so predictions price parked work
  truthfully. (2) PERSISTENT PREFIX CACHE (`prefix_cache_dir=`): on
  stop(), the LRU-cached prefix blocks + index entries are saved under
  the newest param version's content fingerprint; a restarted server
  re-offers the warm blocks (`prefix_restore_hits`), and a restore
  under different params refuses them loudly
  (`KVStateVersionError` — the hot-swap invalidation rule extended
  across restarts). (3) MIGRATION (`migrate_out`/`migrate_in`): a live
  decode-phase request moves between server instances, tag-checked at
  import AND at admission, resumed bit-identical to an uninterrupted
  run — the seam prefill/decode disaggregation and replica fleets
  consume. Extraction is a pure table gather (never a write), so a
  still-pending CoW spare is simply FORGONE — the artifact carries the
  rows, release() returns the spare, and restore re-acquires shared
  leading blocks through the prefix index (refcount++, never
  duplicated) with its own CoW spare if it rides a partial block
  again. All of it composes with chunked prefill and speculation
  (victims/exports are decode-phase slots only; a prefilling slot is
  never spilled — its artifact would be a half-written panel), and the
  non-preempting path stays at ZERO added device dispatches per token
  (counter-pinned: extract/install run only when a spill actually
  happens).
* **Prefix-hit priority admission** (`prefix_priority=`, default on
  where it means something: paged + prefix_cache + chunked_prefill):
  a full-prefix-hit request costs ONE chunk of prefill (chunked paged
  prefill skips resident shared rows — the PR 9 compute reuse), so at
  equal queue position it buys strictly more goodput per slot-second
  than a cold prompt. submit() routes requests whose prompt is fully
  resident in the prefix index (cost == 1 chunk where a cold run would
  pay more) to a priority line served ahead of the primary queue —
  the admission predictor already prices both via `_pf_units`, and an
  admit that actually overtook queued work counts
  `admitted_prefix_priority`. The hit test at submit is advisory (the
  binding match re-runs at admission under the version tag, as
  always): an index entry evicted in between costs the request its
  priority, never its correctness. Priority requests carry the same
  deadline sweep, fail-fast, and drain contracts as the other parked
  lines; the line and the primary queue SHARE the `max_queue` budget
  (neither can stack pending work past the operator's bound); and
  after `_PRIO_BURST` consecutive overtakes the primary head takes
  one turn, so sustained hit traffic degrades cold prompts' position
  but can never starve them outright.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import itertools
import logging
import os
import queue
import threading
import time

import numpy as np

from .. import obs
from .kvstate import (KVStateError, KVStateVersionError,
                      PrefixCacheArtifact, RequestArtifact,
                      artifact_kind)
from .server import (DeadlineExceededError, ReplicaDeadError,
                     RequestDrainedError, RequestMigratedError,
                     ServerClosedError, ServerOverloadedError,
                     _RequestLoop)

log = logging.getLogger(__name__)


def _param_fingerprint(aux, blocks):
    """Content fingerprint of one param version: sha256 over every
    leaf's shape/dtype/bytes. THE durable version tag
    (serving/kvstate.py): the in-process prefix index is namespaced by
    version INDEX, but an index means nothing across a restart or
    between servers — only the weights themselves do. Computed lazily
    once per version (the host transfer is paid only when durable
    state is actually saved/restored, never on the decode path)."""
    import hashlib

    import jax
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves((aux, blocks)):
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


# cancel-race-safe future delivery: the ONE implementation now lives
# in server.py (the base loop's raced-stop paths need it too); the
# names stay importable from here (serving/fleet.py does)
from .server import _fail_future, _resolve_future  # noqa: E402


class _Wake:
    """Sentinel pushed through the PRIMARY queue to wake the idle
    blocking get when a priority submit parks in the side line (the
    get watches only the queue). Its future is born resolved, so every
    existing consumer discards it naturally: `_admit_pending` skips
    done-future requests, and the base `_fail_queued` only fails
    futures that are not done — no consumer needs to know sentinels
    exist."""

    __slots__ = ("future", "deadline", "req_id")

    def __init__(self):
        self.future = cf.Future()
        self.future.set_result(None)
        self.deadline = None
        self.req_id = None


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "future", "deadline", "t_submit",
                 "generated", "slot", "version", "req_id", "t_last_tok",
                 "alloc", "mem_blocked", "pf_next", "pf_wfrom",
                 "work_left", "work_counted", "predicted_done", "klass",
                 "prio_overtook", "pf_quoted", "artifact", "migrated",
                 "progress_base")

    def __init__(self, prompt, max_new, deadline, klass="default"):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.future = cf.Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.generated = []
        self.slot = None
        self.version = None
        self.req_id = None      # assigned at submit (the trace/request id)
        self.t_last_tok = None  # when this request's last token landed
        self.alloc = None       # paged mode: kvpool.PagedAllocation
        self.mem_blocked = False    # counted blocked_on_memory once
        self.pf_next = None     # chunked prefill: next prompt row to run
        self.pf_wfrom = 0       # chunked paged: first row to WRITE
        self.work_left = int(max_new)   # admission backlog accounting
        self.work_counted = False       # work_left added to the backlog?
        self.predicted_done = None      # estimator's completion estimate
        self.klass = klass      # brownout request class
        self.prio_overtook = False  # popped off the priority line ahead
        #                             of queued work; counted at ADMIT
        self.pf_quoted = 1      # prefill units QUOTED at submit (a
        #                         priority hit is quoted 1 chunk; the
        #                         chunked admit retires against this)
        self.artifact = None    # durable KV state parked for resume
        #                         (kvstate.RequestArtifact: preempted
        #                         or migrated-in; None once installed)
        self.migrated = False   # arrived via migrate_in (counted
        #                         `migrated` at restore admission;
        #                         preempted locals count `resumed`)
        self.progress_base = 0  # len(generated) at the last restore:
        #                         a victim must advance
        #                         _PREEMPT_MIN_PROGRESS tokens past
        #                         this before it may be spilled again
        #                         (anti-thrash — see _try_preempt_for)


class ContinuousDecodeServer(_RequestLoop):
    """Token-granularity serving endpoint over a TransformerLM.

    `submit(prompt, max_new_tokens)` returns a Future resolving to the
    full token list (prompt + generated, greedy decode — the
    `generate_batch` contract). `static_batching=True` degrades scheduling
    to gang admission (a new batch only forms when every slot is free) —
    the A/B baseline `tools/serve_ab.py` measures against, through the
    exact same machinery.
    """

    _thread_name = "continuous-decode"
    _default_stop_timeout = 60.0
    # a preemption victim must have decoded this many tokens since its
    # last (re)start before it may be spilled again: each spill's
    # extract+install round-trip is amortized over at least this much
    # progress, so sustained interactive pressure degrades a batch
    # stream's latency but can never pin it in a spill/restore loop
    # with O(1) tokens per full-panel round-trip
    _PREEMPT_MIN_PROGRESS = 4
    # after this many consecutive priority overtakes, the primary
    # queue's head gets one turn: sustained prefix-hit traffic must
    # never starve cold prompts outright (the hit line is a goodput
    # preference, not an SLA inversion)
    _PRIO_BURST = 4
    # fleet prefix tier: max artifact bytes serviced per scheduling
    # iteration by _service_prefix_ops (at least one command always
    # runs) — bounds the extract/install work a burst of peer pulls can
    # steal from one iteration, so the tier can never stall serving
    _PREFIX_IO_BUDGET = 4 << 20

    def __init__(self, lm, slots=4, prompt_buckets=(8, 16, 32),
                 max_queue=64, fault_injector=None, retry_policy=None,
                 metrics=None, stats_reporter=None, report_every=64,
                 static_batching=False, speculate=None, tracer=None,
                 flight_recorder=None, paged=False, block_size=16,
                 n_blocks=None, prefix_cache=True,
                 max_blocks_per_slot=None, chunked_prefill=None,
                 admission=None, brownout=None,
                 default_deadline_ms=None, prefix_priority=True,
                 preempt=False, prefix_cache_dir=None, instance=None,
                 fused_serve=None):
        from ..models.zoo.transformer import (make_block_copy_fn,
                                              make_block_extract_fn,
                                              make_chunked_prefill_fn,
                                              make_fused_decode_fn,
                                              make_paged_decode_fn,
                                              make_paged_fused_decode_fn,
                                              make_paged_install_fn,
                                              make_paged_prefill_fn,
                                              make_paged_verify_fn,
                                              make_prefill_fn,
                                              make_slot_decode_fn)
        from .admission import AdmissionController
        from .speculate import as_speculator
        import jax

        self._tracer = tracer if tracer is not None else obs.TRACER
        self._flight = flight_recorder
        self.lm = lm
        self.slots = int(slots)
        self.max_len = int(lm.aux["pos"].shape[0])
        self.prompt_buckets = tuple(sorted(int(b) for b in prompt_buckets))
        if self.prompt_buckets[-1] > self.max_len:
            raise ValueError(f"largest prompt bucket "
                             f"{self.prompt_buckets[-1]} > model max_len "
                             f"{self.max_len}")
        self._injector = fault_injector
        self._retry = retry_policy
        from .metrics import ServingMetrics
        # instance identity (the fleet plane, obs/fleet.py): names this
        # server in federated metrics (ServingMetrics endpoint name),
        # merged traces (per-instance process groups), and — when set
        # EXPLICITLY — the request/trace ids themselves ("i0-7"), so a
        # request migrated between named instances keeps one globally
        # unique trace id across both servers' traces. Default (None)
        # keeps plain integer ids: single-server behavior unchanged.
        self.metrics = metrics or ServingMetrics(name=instance)
        self.instance = (str(instance) if instance is not None
                         else self.metrics.name)
        self._named_instance = instance is not None
        self._reporter = stats_reporter
        self._report_every = max(1, int(report_every))
        self._static = bool(static_batching)

        n_heads = lm.n_heads
        self._n_heads = n_heads
        self._d_model = int(lm.aux["tok"].shape[1])
        self._cache_dtype = lm.aux["tok"].dtype
        self._n_layers = len(lm.blocks)
        self._versions = [(lm.aux, lm.blocks)]   # index = param version

        # paged KV cache (module docstring): arena + block tables
        # replace the fixed per-slot cache; admission gates on free
        # blocks. Config resolves BEFORE _reset_device_state builds the
        # device state from it.
        self._paged = bool(paged)
        self._block_size = int(block_size)
        if self._paged and self._block_size < 1:
            raise ValueError(f"need block_size >= 1, got {block_size}")
        # default arena == the fixed-slot footprint at the same slot
        # count (equal bytes); callers scale slots/arena independently
        self._n_blocks = (int(n_blocks) if n_blocks is not None else
                          -(-self.slots * self.max_len
                            // self._block_size))
        # per-slot logical capacity: enough table entries for max_len
        # rows (the submit() length guard caps every stream there)
        self._nb_slot = (int(max_blocks_per_slot)
                         if max_blocks_per_slot is not None else
                         -(-self.max_len // self._block_size))
        self._prefix_cache = bool(prefix_cache)
        self._mem_wait = collections.deque()     # blocked on FREE BLOCKS

        # overload control (module docstring; serving/admission.py):
        # chunk size, admission predictor, brownout policy, default
        # per-request deadline (the InferenceServer contract)
        self._chunk = None if chunked_prefill is None \
            else int(chunked_prefill)
        if self._chunk is not None and self._chunk > self.max_len:
            raise ValueError(f"chunked_prefill {self._chunk} > model "
                             f"max_len {self.max_len}")
        self._admission = (AdmissionController() if admission is True
                           else admission)
        if self._admission is not None and \
                self._admission.estimator.slots is None:
            # predictions scale capacity by the scheduling width; a
            # caller-built controller usually leaves it for us to fill
            self._admission.estimator.slots = self.slots
        self._brownout = brownout
        self.default_deadline = (None if default_deadline_ms is None
                                 else float(default_deadline_ms) / 1e3)
        self._defer_q = collections.deque()      # brownout-deferred line
        # prefix-hit priority admission (module docstring): effective
        # only where a full-prefix hit really is cheaper — paged prefix
        # cache + chunked prefill, where a full hit costs ONE chunk
        # while a cold prompt pays ceil(P/C)
        self._prefix_priority = (bool(prefix_priority) and self._paged
                                 and self._prefix_cache
                                 and self._chunk is not None)
        self._prio_q = collections.deque()       # prefix-hit fast line
        self._prio_streak = 0   # consecutive genuine overtakes (anti-
        #                         starvation: see _next_request)
        # durable KV state (module docstring; serving/kvstate.py):
        # preemption policy, the resume line, migration plumbing, and
        # the persistent prefix-cache directory. The preempt verb needs
        # BOTH the paged pool (fixed-slot state has no extractable
        # block set) and a brownout policy (class ranking IS the
        # policy; without one no class may preempt another and the
        # flag would be a silent no-op).
        self._preempt_on = bool(preempt)
        if self._preempt_on and not self._paged:
            raise ValueError("preempt=True requires paged=True (only a "
                             "block-table KV set can be spilled)")
        if self._preempt_on and brownout is None:
            raise ValueError("preempt=True requires a brownout= policy: "
                             "BrownoutPolicy.may_preempt ranks request "
                             "classes, and without a ranking nothing "
                             "may ever be preempted")
        self._prefix_dir = (None if prefix_cache_dir is None
                            else str(prefix_cache_dir))
        if self._prefix_dir is not None and not (
                self._paged and self._prefix_cache):
            raise ValueError("prefix_cache_dir requires paged=True with "
                             "prefix_cache=True (there is no prefix "
                             "cache to persist otherwise)")
        self._resume_q = collections.deque()     # serve-thread ONLY:
        #   spilled requests (artifact set) awaiting blocks + a slot
        self._migrate_in_q = collections.deque()  # client -> serve
        #   staging for migrate_in (drained into _resume_q by the loop
        #   so _resume_q never races a client append)
        self._migrate_cmds = collections.deque()  # (future, reply)
        self._prefix_cmds = collections.deque()  # fleet prefix tier:
        #   ("export", key, max_bytes, reply) | ("adopt", art, reply) —
        #   serviced at the iteration boundary under a per-iteration
        #   bytes budget so the tier can never stall serving
        self._prefix_io_budget = self._PREFIX_IO_BUDGET
        self._drain_cmds = collections.deque()   # (migrate, reply):
        #   the fleet drain verb — serve thread hands back EVERY
        #   admitted request in one pass (see drain())
        self._killed = False    # crash-injection verb fired (kill());
        #   terminal — a killed replica never serves again
        self._tag_cache = {}    # version index -> param fingerprint
        self._prefix_saved = True   # nothing to save before start()
        self._gate_key = None   # preempting-gate rescan guard: the
        #   (pool, progress, depth) signature of the last full scan
        #   that admitted nothing — identical signature => skip
        self._work_lock = threading.Lock()
        self._work_tokens = 0   # work-unit backlog (queued + live)
        # admission hysteresis: any actual eviction/queue expiry
        # CONFIRMS overload and tightens prediction shedding to exactly
        # the deadline budget for this long (admission.py should_shed)
        self._thrash_until = 0.0

        self._reset_device_state()
        # ONE decode program for the life of the server (fixed slot count;
        # params are arguments, so hot swap reuses it). Cache and pos are
        # donated — they are THE device state, rebound every iteration.
        if self._paged:
            # (aux, blocks, cache, btabs, pos, tok, active)
            self._step = jax.jit(
                make_paged_decode_fn(n_heads, self._block_size),
                donate_argnums=(2, 4))
        else:
            self._step = jax.jit(make_slot_decode_fn(n_heads),
                                 donate_argnums=(2, 3))
        # chunked prefill (module docstring): ONE verify-shaped chunk
        # program for the life of the server — every prefilling slot
        # advances C prompt rows per scheduling iteration through it,
        # interleaved with the decode dispatches. Cache and pos are
        # donated exactly like the decode step's: chunk dispatches run
        # inside the scheduler loop, whose terminal-failure path resets
        # the whole device state anyway.
        if self._chunk is None:
            self._chunk_step = None
        elif self._paged:
            self._chunk_step = jax.jit(
                make_chunked_prefill_fn(n_heads, self._chunk,
                                        self._block_size),
                donate_argnums=(2, 4))
        else:
            self._chunk_step = jax.jit(
                make_chunked_prefill_fn(n_heads, self._chunk),
                donate_argnums=(2, 3))
        # rolling window of recent SLO outcomes (1 met / 0 missed): the
        # brownout policy's attainment signal — RECENT, not all-time,
        # so recovery after a burst reopens admission
        self._slo_recent = collections.deque(maxlen=64)
        # speculative decoding (serving/speculate.py): ONE K-wide verify
        # program replaces the 1-token step for every iteration — drafts
        # in, 1..K accepted tokens out per slot per dispatch, token
        # streams pinned bit-identical to the plain step. Fixed layout:
        # the model's OWN cached verify jit (`_spec_verify`), shared
        # with generate(draft=...) so the same (model, K) never
        # compiles twice. Paged layout: the block-table verify twin
        # (`make_paged_verify_fn`), jitted here because block_size is
        # server config; cache and pos donated exactly like the decode
        # step's — they are THE device state, and the loop's
        # terminal-failure path resets all of it anyway.
        self._spec = as_speculator(speculate)
        if self._spec is None:
            self._verify = None
        elif self._paged:
            self._verify = jax.jit(
                make_paged_verify_fn(n_heads, self._spec.k,
                                     self._block_size),
                donate_argnums=(2, 4))
        else:
            self._verify = lm._spec_verify(self._spec.k)
        # fused decode windows (module docstring; ISSUE 18): scan K
        # decode iterations into ONE device dispatch — nn/fused.py's
        # fused_steps applied to serving. K=1 is the plain path exactly
        # (no window program is even built), so the flag defaults to
        # zero behavior change. Slot membership is static inside a
        # window: admissions, evictions, chunked-prefill transitions,
        # and deadline sweeps all land at window boundaries
        # (_loop_once runs them once per pass, and one fused pass IS
        # one window). Cache and pos are donated exactly like the
        # 1-wide step's — same device state, same terminal-failure
        # reset contract.
        self._fused = 1 if fused_serve is None else int(fused_serve)
        if self._fused < 1:
            raise ValueError(f"fused_serve must be >= 1, got "
                             f"{fused_serve}")
        if self._fused > 1 and self._spec is not None:
            # the PR 8 composition precedent: refuse LOUDLY at the
            # constructor instead of silently picking one mode — a
            # fused window advances every slot one token per scanned
            # step, while speculation needs fresh host-side drafts
            # every iteration; the two cannot share a dispatch yet
            raise ValueError(
                "fused_serve > 1 does not compose with speculate= "
                "(a fused window cannot take fresh drafts mid-scan); "
                "configure one or the other")
        if self._fused > 1:
            if self._paged:
                # (aux, blocks, cache, btabs, pos, tok, active, steps,
                #  wto)
                self._window_step = jax.jit(
                    make_paged_fused_decode_fn(
                        n_heads, self._block_size, self._fused),
                    donate_argnums=(2, 4))
            else:
                # (aux, blocks, cache, pos, tok, active, steps)
                self._window_step = jax.jit(
                    make_fused_decode_fn(n_heads, self._fused),
                    donate_argnums=(2, 3))
        else:
            self._window_step = None
        # per-iteration wall-time EWMA: the fused deadline clamp's rate
        # estimate (None until the first token-bearing iteration)
        self._iter_ewma = None
        self._prefills = {}                      # bucket -> jitted program
        # Paged prefill mirrors the fixed path's two-program shape:
        # a pure-compute prefill returning panels (no arena argument —
        # an admission-time failure must fail ONLY that request, and a
        # program that neither takes nor returns the arena trivially
        # leaves it valid) plus a small DONATED install scatter that
        # aliases the arena in place. Fusing install into the prefill
        # would force the arena through an UNDONATED output and copy
        # every untouched row — the whole pool's bytes — per admission.
        # The CoW copy is donated for the same reason; it runs inside
        # _decode_iteration, whose failure path — like the donated
        # decode step's — resets the entire device state anyway.
        if self._paged:
            self._make_prefill = lambda: jax.jit(make_paged_prefill_fn(
                n_heads))
            self._paged_install = jax.jit(
                make_paged_install_fn(self._block_size),
                donate_argnums=(0,))
            self._cow_copy = jax.jit(
                make_block_copy_fn(self._block_size),
                donate_argnums=(0,))
            # durable-KV extract: a pure [NB]-table gather (arena read,
            # never donated) — one compiled program per server, shared
            # by preemption, migration export, and the prefix-cache
            # save (which batches cached blocks through the same table
            # width)
            self._extract = jax.jit(
                make_block_extract_fn(self._block_size))
        else:
            self._make_prefill = lambda: jax.jit(make_prefill_fn(
                n_heads, self.max_len))

            def install(cache, rows, s):
                return [{"k": c["k"].at[s].set(r["k"][0]),
                         "v": c["v"].at[s].set(r["v"][0])}
                        for c, r in zip(cache, rows)]
            # only the cache is donated: its buffers alias the output
            # exactly, while the [1, L, H, hd] prefill rows never could
            self._install = jax.jit(install, donate_argnums=(0,))

        self._swap_lock = threading.Lock()
        self._init_loop(max_queue)
        if self._named_instance:
            # namespaced request/trace ids: every span lane and trace
            # context this server emits is unique across the fleet
            self._req_ids = (f"{self.instance}-{n}"
                             for n in itertools.count())
        if self._prefix_dir is not None and \
                artifact_kind(self._prefix_dir) == "prefix_cache":
            # warm start: a committed snapshot exists — restore it into
            # the fresh pool BEFORE serving begins. A version mismatch
            # raises KVStateVersionError out of the constructor (LOUD:
            # the operator pointed a new model at an old cache; zero
            # silent reuse). An absent/partial snapshot is a cold
            # start, not an error.
            self.restore_prefix_cache(self._prefix_dir)

    # -- client API ----------------------------------------------------
    def submit(self, prompt, max_new_tokens, deadline_ms=None,
               klass="default"):
        """Enqueue one decode request; Future resolves to the full token
        list (prompt + `max_new_tokens` greedy continuations).
        `deadline_ms` falls back to the server's `default_deadline_ms`;
        `klass` is the brownout request class (ignored without a
        `brownout=` policy)."""
        if not self._running:
            raise ServerClosedError("server is not running")
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) > self.prompt_buckets[-1]:
            raise ValueError(f"prompt length {len(prompt)} exceeds the "
                             f"largest bucket {self.prompt_buckets[-1]}")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt+new tokens ({len(prompt)}+{max_new_tokens}) "
                f"exceed max_len {self.max_len}")
        if self._paged:
            # never-fits check: a request whose worst-case block table
            # exceeds the WHOLE pool would wait forever in the memory
            # queue — shed it loudly at submit instead
            need = self._pool.blocks_needed(
                len(prompt) + int(max_new_tokens) - 1)
            if need > self._n_blocks:
                self.metrics.count("shed_blocks")
                raise ServerOverloadedError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self._n_blocks} (block_size="
                    f"{self._block_size})")
            if need > self._nb_slot:
                # the per-slot block TABLE is the other hard ceiling: a
                # caller-tuned max_blocks_per_slot below ceil(max_len/bs)
                # must shed here, not crash the admission thread on the
                # table write
                self.metrics.count("shed_blocks")
                raise ServerOverloadedError(
                    f"request needs {need} KV blocks but a slot's table "
                    f"holds {self._nb_slot} (max_blocks_per_slot)")
        if self._injector is not None:
            self._injector.fire("serve.request")
        self.metrics.count("received")
        now = time.monotonic()
        if deadline_ms is not None:
            dl = now + deadline_ms / 1e3
        else:
            dl = (now + self.default_deadline
                  if self.default_deadline is not None else None)
        deferred = False
        if self._brownout is not None:
            from .admission import DEFER, SHED
            # maxsize <= 0 is queue.Queue's unbounded convention: depth
            # pressure is undefined there, so the depth thresholds never
            # engage (attainment brownout still can). The priority line
            # counts toward depth: its requests bypass the queue.Queue
            # but are pending work all the same.
            frac = ((self._q.qsize() + len(self._prio_q))
                    / self._q.maxsize if self._q.maxsize > 0 else 0.0)
            decision = self._brownout.decide(
                klass, frac, self._recent_attainment())
            if decision == SHED:
                self.metrics.count("shed_brownout")
                self.metrics.record_queue_depth(self._pending_depth())
                raise ServerOverloadedError(
                    f"brownout: class {klass!r} shed at queue depth "
                    f"{frac:.0%}")
            deferred = decision == DEFER
        prio = False
        if self._prefix_priority and not deferred \
                and len(prompt) > self._chunk:
            # prefix-hit priority (module docstring): a FULL-prefix hit
            # leaves at most one chunk of prefill where a cold prompt
            # pays ceil(P/C) — route it to the fast line. Advisory test
            # under the newest version tag; the binding match re-runs
            # at admission, so an index entry evicted in between costs
            # priority, never correctness. Prompts that fit one chunk
            # anyway gain nothing and stay FIFO. The lookup runs on the
            # CLIENT thread against pool dicts the serve thread
            # mutates: a raced resize mid-walk degrades to FIFO (the
            # same cost as a missed match), never to a failed submit.
            with self._swap_lock:
                vidx = len(self._versions) - 1
            try:
                rows = self._pool.match_prefix(prompt, tag=vidx)[1]
            except RuntimeError:    # dict resized during the walk
                rows = 0
            start = min(rows, len(prompt) - 1)
            prio = len(prompt) - start <= self._chunk
        if self._admission is not None and dl is not None \
                and not deferred:
            # predicted completion at ENQUEUE: work ahead (queued + live
            # generated-token backlog) plus this request's own budget,
            # over the measured aggregate rate. Shedding here — before
            # the request costs a queue slot, blocks, or decode work —
            # is the whole point; the estimator's conservatism contract
            # (sheds late, never a request solo execution could finish
            # in time) lives in serving/admission.py and is pinned by
            # property test. Submit-time sheds stay out of slo_total,
            # matching the queue-full precedent: attainment is over
            # ADMITTED requests.
            backlog = self._work_tokens
            # the predictor prices BOTH prefill costs: a priority-line
            # prefix hit re-runs one chunk, a cold prompt its full
            # chunk count — so a hit request's shed decision reflects
            # the cheaper admission it will actually get
            own = int(max_new_tokens) + (1 if prio else
                                         self._pf_units(len(prompt)))
            if self._admission.should_shed(
                    backlog, own, dl - now,
                    strict=now < self._thrash_until):
                self.metrics.count("shed_predicted")
                pred = self._admission.predict_seconds(backlog, own)
                raise ServerOverloadedError(
                    f"predicted completion in {pred * 1e3:.0f}ms behind "
                    f"{backlog} backlog work units cannot make the "
                    f"{(dl - now) * 1e3:.0f}ms deadline budget")
        req = _DecodeRequest(prompt, max_new_tokens, dl, klass=klass)
        # work is counted in ITERATION-EQUIVALENT units: generated
        # tokens plus the prefill dispatches (chunks) the prompt will
        # consume — a slot spends one scheduling iteration per unit, so
        # backlog predictions see prefill-heavy queues at true size.
        # A priority-line hit is QUOTED its real 1-chunk cost (matching
        # the shed decision above), so the prediction stamped below and
        # the bias loop's (predicted - actual) error measure the same
        # request the admission decision admitted — full-cost phantom
        # units here would read systematically pessimistic for every
        # hit and mask genuine optimism from cold requests.
        req.pf_quoted = 1 if prio else self._pf_units(len(prompt))
        req.work_left += req.pf_quoted
        if self._admission is not None and not deferred:
            # DEFERRED requests carry no prediction: their service time
            # is brownout policy (they yield until the primary queue
            # empties), and stamping a primary-queue prediction on them
            # would feed huge phantom "optimism" errors into the bias
            # loop and thrash window when they complete late BY DESIGN
            pred = self._admission.predict_seconds(
                self._work_tokens, req.work_left)
            if pred is not None:
                # stamped for the (predicted - actual) error histogram —
                # recorded for every admitted PRIMARY-line prediction,
                # deadline-tight or not, so the estimator's drift is
                # visible even while nothing is being shed
                req.predicted_done = now + pred
        # backlog accounting: the request's whole unit budget joins the
        # backlog now and retires unit-by-unit as it prefills/decodes;
        # ANY resolution of the future (result, failure, caller cancel)
        # retires the remainder exactly once, so the counter cannot
        # drift under sheds, evictions, or stop(). DEFERRED requests
        # join only when they leave the deferred line (_next_request):
        # they run BEHIND the primary queue, so counting them ahead of
        # primary submissions would invert the priority inside
        # predictions and shed feasible primary requests
        if not deferred:
            with self._work_lock:
                self._work_tokens += req.work_left
                req.work_counted = True
        req.future.add_done_callback(
            lambda _f, r=req: self._retire_work(r))
        try:
            if deferred:
                return self._enqueue_deferred(req)
            if prio:
                return self._enqueue_priority(req)
            return self._enqueue(req)
        except BaseException:
            self._retire_work(req)
            raise

    def _deadline_miss(self, req, now, thrash=True):
        """The ONE deadline-expiry bookkeeping path for all four shed
        sites (submit queue, memory gate, deferred line, mid-decode):
        counters, SLO miss, the rolling attainment window, admission
        feedback, and — unless the expiry is brownout deferral starving
        a class by POLICY rather than overload — the admission thrash
        window."""
        self.metrics.count("shed_deadline")
        self.metrics.record_slo_miss()
        self._slo_recent.append(0)
        self._admission_outcome(req, now, completed=False)
        if thrash:
            self._thrash_until = now + 0.5

    def _admission_outcome(self, req, now, completed):
        """Close one prediction's feedback loop: the signed
        (predicted - actual) error at completion; at an eviction/expiry
        the actual end is unknown but >= now, so a NEGATIVE
        (predicted - now) is a CERTAIN lower bound on the optimism —
        recorded too (an uninformative positive bound is dropped, and
        skipping evictions entirely would survivor-bias the histogram
        toward pessimism). Both the histogram (observability) and the
        controller's bias loop (self-correction) are fed here."""
        if req.predicted_done is None:
            return
        err = req.predicted_done - now
        req.predicted_done = None
        if not completed and err >= 0:
            return
        self.metrics.record_admission_error(err * 1e3)
        if self._admission is not None:
            self._admission.observe_error(err)

    def _pf_units(self, plen):
        """Prefill cost of a prompt in iteration-equivalent work units:
        its chunk count when it will take the chunked path (longer than
        one chunk — the sizing rule in _admit), one one-shot dispatch
        otherwise."""
        if self._chunk is not None and int(plen) > self._chunk:
            return -(-int(plen) // self._chunk)
        return 1

    def _retire_work(self, req):
        """Remove a request's unproduced work units from the admission
        backlog (idempotent — work_left zeroes on first retirement; a
        still-deferred request was never counted in)."""
        with self._work_lock:
            if req.work_counted:
                self._work_tokens -= req.work_left
            req.work_left = 0

    def _spend_work(self, req, units=1):
        """Retire `units` of a request's backlog as they are served."""
        with self._work_lock:
            n = min(units, req.work_left)
            req.work_left -= n
            self._work_tokens -= n

    def _recent_attainment(self):
        """Mean of the rolling SLO-outcome window (None while empty):
        the brownout policy's attainment input."""
        win = list(self._slo_recent)
        return (sum(win) / len(win)) if win else None

    def _enqueue_deferred(self, req):
        """Park a brownout-DEFERRED request in the side line the
        scheduler serves only when the primary queue is empty. Same
        contracts as `_enqueue`: bounded (sheds loudly when the line is
        as deep as the queue), traced, and a raced stop() fails the
        future rather than stranding the caller."""
        if req.req_id is None:
            req.req_id = next(self._req_ids)
        if 0 < self._q.maxsize <= len(self._defer_q):
            self.metrics.count("shed_queue_full")
            self.metrics.record_queue_depth(self._q.maxsize)
            raise ServerOverloadedError(
                f"deferred line full ({self._q.maxsize} parked)")
        self.metrics.count("deferred")
        self._defer_q.append(req)
        tr = self._tracer
        if tr.enabled:
            tr.instant("serve.enqueue", cat="serve",
                       track=f"req-{req.req_id}", trace_id=req.req_id)
        if not self._running:
            # _fail_future: cancel-race-safe (the base _enqueue rule)
            _fail_future(req.future, ServerClosedError(
                "server stopped during submit"))
            raise ServerClosedError("server stopped during submit")
        return req.future

    def _pending_depth(self):
        """Enqueue-time depth includes every parked line — the priority
        line and the resume/migrate-in lines are pending work the gauge
        must not hide — and the one base-class sample per enqueue stays
        the ONLY sample."""
        return (self._q.qsize() + len(self._prio_q)
                + len(self._resume_q) + len(self._migrate_in_q))

    def _shed_if_lines_full(self):
        """The ONE shared-budget check every admission path runs (plain
        submit, priority line, migrate_in): the primary queue and ALL
        parked lines — priority, resume, migrate-in staging — together
        may never stack pending work past `max_queue`, otherwise parked
        hits/artifacts plus queued colds would multiply the operator's
        backpressure bound (and the resume/staging lines hold full KV
        panels in host memory). (Two racing submits can each pass the
        sum check — the same benign width every parked-line bound has;
        the Queue's own put_nowait still hard-caps the primary line.)"""
        if 0 < self._q.maxsize <= self._pending_depth():
            self.metrics.count("shed_queue_full")
            self.metrics.record_queue_depth(self._pending_depth())
            raise ServerOverloadedError(
                f"queue full ({self._q.maxsize} pending incl. parked "
                f"lines)")

    def _enqueue(self, req):
        """The primary enqueue with the budget shared BOTH ways (see
        `_shed_if_lines_full`)."""
        self._shed_if_lines_full()
        return super()._enqueue(req)

    def _enqueue_priority(self, req):
        """Park a prefix-hit request in the PRIORITY line served ahead
        of the primary queue (module docstring). Same contracts as
        `_enqueue`: bounded (the line and the primary queue share the
        queue budget — a full house sheds loudly), depth-sampled,
        traced, and a raced stop() fails the future rather than
        stranding the caller."""
        if req.req_id is None:
            req.req_id = next(self._req_ids)
        self._shed_if_lines_full()
        self._prio_q.append(req)
        if not any(r is not None for r in self._slot_req):
            # wake a possibly idle-BLOCKED serve loop: the idle wait
            # blocks on the primary queue only, and without a nudge a
            # hit landing on an idle server would eat the whole idle
            # timeout — latency the cold path never pays. Only the
            # idle loop needs it (a busy loop checks the priority line
            # every iteration without blocking), and only then is the
            # sentinel consumed promptly — pushed while busy it would
            # sit in the queue eating backpressure budget. The
            # idle-check race (loop going idle right after we look)
            # costs at most one 50 ms idle timeout, the pre-fix cost.
            try:
                self._q.put_nowait(_Wake())
            except queue.Full:
                pass
        self.metrics.record_queue_depth(self._pending_depth())
        tr = self._tracer
        if tr.enabled:
            tr.instant("serve.enqueue", cat="serve",
                       track=f"req-{req.req_id}", trace_id=req.req_id)
        if not self._running:
            # _fail_future: cancel-race-safe (the base _enqueue rule)
            _fail_future(req.future, ServerClosedError(
                "server stopped during submit"))
            raise ServerClosedError("server stopped during submit")
        return req.future

    def generate(self, prompt, max_new_tokens, deadline_ms=None,
                 timeout=None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    # -- hot swap ------------------------------------------------------
    def swap(self, new_lm):
        """Route NEW requests to `new_lm`'s params while slots already
        decoding drain on the version they started with (dual-version
        dispatch — module docstring). Structure/shape mismatch raises."""
        import jax
        with self._swap_lock:
            if self._injector is not None:
                self._injector.fire("serve.swap")
            new = (new_lm.aux, new_lm.blocks)
            old_l, old_t = jax.tree_util.tree_flatten(self._versions[-1])
            new_l, new_t = jax.tree_util.tree_flatten(new)
            if old_t != new_t:
                raise ValueError("swap rejected: param tree structure "
                                 "differs from the serving model")
            for o, n in zip(old_l, new_l):
                if o.shape != n.shape or o.dtype != n.dtype:
                    raise ValueError(f"swap rejected: leaf mismatch "
                                     f"{n.shape}/{n.dtype} vs serving "
                                     f"{o.shape}/{o.dtype}")
            self._versions.append(new)
            self.metrics.count("swaps")

    def current_params(self):
        """(aux, blocks) of the NEWEST param version — the canary
        rollout's rollback snapshot (`serving/fleet.py` swaps it back
        through a duck-typed params view when the gate trips)."""
        with self._swap_lock:
            return self._versions[-1]

    # -- fleet verbs (serving/fleet.py) --------------------------------
    @property
    def paged(self):
        """Whether this server runs the block-table KV cache — the
        capability gate for migrate_in/migrate_out/drain(migrate=True)
        (the fleet router and the wire HELLO both read it; reaching
        for `_paged` from outside was the old way)."""
        return self._paged

    @property
    def alive(self):
        """True while the serve loop is running on a live thread — the
        fleet router's liveness probe. A killed or crashed loop reads
        False even before anyone calls stop()."""
        t = self._thread
        return bool(self._running and not self._killed
                    and t is not None and t.is_alive())

    def kill(self):
        """Abrupt replica death — the crash-injection verb the fleet's
        `fleet.replica` FaultInjector sever action lands on. The serve
        loop exits at the next iteration boundary and EVERY in-flight,
        parked, and queued future fails loudly with `ReplicaDeadError`;
        nothing drains and nothing persists (a real crash would not).
        Terminal and idempotent: a killed server refuses start().
        Thread-safe; callable from any thread including callbacks on
        this server's own futures."""
        self._killed = True
        self._running = False
        self._drain_on_stop = False
        try:                        # wake an idle-blocked loop
            self._q.put_nowait(_Wake())
        except queue.Full:
            pass
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(10.0)
        if t is None or not t.is_alive() \
                or t is threading.current_thread():
            # the loop is gone (or IS this thread): nobody else will
            # fail the stragglers — do it here (idempotent: resolved
            # futures are skipped)
            self._die_now()

    def _die_now(self):
        """Fail every request this server still holds with the crash
        error (kill()'s delivery half — runs on the serve thread when
        the loop notices `_killed`, or on the killer's thread once the
        loop is gone)."""
        exc = ReplicaDeadError(f"replica {self.instance!r} crashed")
        n_failed = 0
        for s, r in enumerate(self._slot_req):
            if r is not None and _fail_future(r.future, exc):
                n_failed += 1
            self._slot_req[s] = None
        if n_failed:
            self.metrics.count("failed", n_failed)
        self._fail_parked(exc)
        super()._fail_queued(exc)

    def drain(self, migrate=None, timeout=60.0):
        """Hand off EVERY admitted request in ONE verb, then stop.

        Returns ``(migrated, replayed)``:

          * ``migrated`` — list of ``(local_future, RequestArtifact)``
            for DECODE-PHASE requests (live slots plus the parked
            resume line): each local future fails with
            `RequestMigratedError`; `migrate_in(artifact)` on another
            server resumes the stream bit-identically (the durable-KV
            pin, now exercised across the router).
          * ``replayed`` — list of ``(local_future, spec)`` for queued,
            deferred, priority-parked, memory-blocked, and PREFILLING
            requests. A half-written prefill panel is NEVER an
            artifact (the preemption victim rule, enforced at this
            seam too), so these replay from their prompt instead:
            each local future fails with `RequestDrainedError` and
            ``spec`` carries ``{"prompt", "max_new", "deadline"
            (absolute monotonic or None), "klass"}`` ready to resubmit
            on a survivor — deterministic greedy decode makes the
            replayed stream equal the uninterrupted one.

        `migrate` defaults to the cache layout's capability (paged
        servers migrate, fixed-slot servers replay everything);
        migrate=True on a fixed-slot server raises. The extraction
        runs on the serve thread between iterations (the migrate_out
        machinery); on return the loop is STOPPED and the server holds
        zero requests."""
        migrate = self._paged if migrate is None else bool(migrate)
        if migrate and not self._paged:
            raise ValueError("drain(migrate=True) requires paged=True "
                             "(only a block-table KV set can leave the "
                             "arena); fixed-slot servers drain with "
                             "migrate=False — everything replays")
        if not self._running:
            raise ServerClosedError("server is not running")
        reply = cf.Future()
        self._drain_cmds.append((migrate, reply))
        try:                        # wake an idle-blocked loop
            self._q.put_nowait(_Wake())
        except queue.Full:
            pass
        migrated, replayed = reply.result(timeout)
        self.stop(drain=False, timeout=timeout)
        return migrated, replayed

    def _service_drain(self):
        """Serve-thread half of `drain()`."""
        while self._drain_cmds:
            migrate, reply = self._drain_cmds.popleft()
            try:
                out = self._drain_now(migrate)
            except BaseException as e:  # noqa: BLE001 — reply carries it
                if not reply.done():
                    reply.set_exception(e)
            else:
                if not reply.done():
                    reply.set_result(out)

    def _drain_now(self, migrate):
        migrated, replayed = [], []

        def spec_of(r):
            return {"prompt": list(r.prompt), "max_new": r.max_new,
                    "deadline": r.deadline, "klass": r.klass}

        def hand_off(r, art):
            """One request out the door: decode-phase state with rows
            in hand migrates (when asked), everything else replays."""
            if migrate and art is not None:
                if _fail_future(r.future, RequestMigratedError(
                        "request drained to another replica")):
                    migrated.append((r.future, art))
                    self.metrics.count("migrated_out")
                    self._mark_migrate_out(r)
            elif _fail_future(r.future, RequestDrainedError(
                    "request replayed on another replica (queued/"
                    "prefill-phase state is never migrated)")):
                replayed.append((r.future, spec_of(r)))

        # live slots: decode-phase slots carry extractable rows; a
        # PREFILLING slot's panel is half-written — never an artifact
        for s, r in enumerate(self._slot_req):
            if r is None:
                continue
            if r.future.done():
                self._free_slot(s)
                continue
            art = None
            if migrate and r.pf_next is None and r.generated:
                art = self._extract_artifact(s)
            hand_off(r, art)
            self._free_slot(s)
        # parked artifacts (resume line + migrate-in staging) already
        # ARE their own baton
        while self._migrate_in_q:
            self._resume_q.append(self._migrate_in_q.popleft())
        while self._resume_q:
            r = self._resume_q.popleft()
            if r.future.done():
                continue
            art, r.artifact = r.artifact, None
            hand_off(r, art)
        # queued lines: no KV state anywhere — replay specs
        for dq in (self._mem_wait, self._prio_q, self._defer_q):
            while dq:
                try:
                    r = dq.popleft()
                except IndexError:
                    break
                if not r.future.done():
                    hand_off(r, None)
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if not r.future.done():     # skips _Wake sentinels too
                hand_off(r, None)
        self._gc_versions()
        return migrated, replayed

    # -- durable KV state (serving/kvstate.py) -------------------------
    def start(self):
        if self._killed:
            raise ServerClosedError(
                "replica was killed; build a new server instead of "
                "restarting a crashed one")
        # a (re)started server has live state the next clean stop must
        # persist again
        self._prefix_saved = self._prefix_dir is None
        return super().start()

    def stop(self, drain=True, timeout=None):
        """Stop the loop (base semantics), then — when constructed with
        `prefix_cache_dir=` and the loop really exited — persist the
        prefix cache so the next server instance warm-starts. The save
        runs on the CALLER's thread against a dead loop (the serve
        thread owned the arena until it exited); a join timeout skips
        it (the loop still owns the arena) but a RETRIED stop() after
        the drain finally finishes performs it — the `_prefix_saved`
        flag, not a was-running snapshot, decides, so a slow drain
        cannot silently cost the warm start. A failed save is logged,
        not raised: stop() must tear the server down regardless."""
        super().stop(drain=drain, timeout=timeout)
        t = self._thread
        if (self._prefix_dir is not None and not self._prefix_saved
                and not self._running
                and (t is None or not t.is_alive())):
            self._prefix_saved = True
            try:
                self.save_prefix_cache(self._prefix_dir)
            except Exception:   # noqa: BLE001 — teardown must finish
                log.exception("prefix-cache save failed at stop()")

    def _version_tag(self, vidx):
        """Content fingerprint of param version `vidx` — the durable
        tag artifacts carry (computed once per version, cached)."""
        tag = self._tag_cache.get(vidx)
        if tag is None:
            with self._swap_lock:
                ver = self._versions[vidx]
            if ver is None:
                raise KVStateError(f"param version {vidx} already "
                                   f"drained; nothing to fingerprint")
            tag = self._tag_cache[vidx] = _param_fingerprint(*ver)
        return tag

    def _extract_artifact(self, slot):
        """Pull `slot`'s complete KV state to host as a
        `RequestArtifact` (serve thread only; decode phase only). One
        extract dispatch — a pure table gather, so a still-pending CoW
        spare needs no materialization (the shared partial block is
        READ; restore re-acquires shared rows through the prefix index
        or re-installs them privately) and the arena is never at risk
        from a failed call."""
        import jax.numpy as jnp
        r = self._slot_req[slot]
        pos = len(r.prompt) + len(r.generated) - 1
        tab = np.zeros((self._nb_slot,), np.int32)
        tab[:len(r.alloc.ids)] = r.alloc.ids
        with self._tracer.span("decode.extract", cat="serve",
                               track="server", trace_id=r.req_id,
                               slot=slot, rows=pos):
            panels = self._extract(self._cache, jnp.asarray(tab))
        # slice to the frontier on host: rows >= pos are dead rows
        # (rejected speculative suffixes, chunk padding) or zero-table
        # resolutions — garbage by contract, never serialized
        panels = [(np.asarray(k)[:pos].copy(), np.asarray(v)[:pos].copy())
                  for k, v in panels]
        # the Dapper baton: the artifact carries the request's trace id
        # + origin lane, so the importing server continues the SAME
        # `req-<id>` lane under the same trace id and the two saved
        # traces stitch into one timeline (obs.fleet.merge_traces).
        # Host-side metadata only — zero device work, and a consumer
        # that never traces simply ignores it.
        art = RequestArtifact(r.prompt, r.generated, r.max_new,
                              self._version_tag(r.version),
                              self._block_size, panels, klass=r.klass,
                              trace={"trace_id": r.req_id,
                                     "parent_span": f"req-{r.req_id}",
                                     "origin": self.instance})
        self.metrics.count("spill_bytes", art.nbytes)
        return art

    def _preempt_slot(self, slot):
        """PAUSE `slot`'s request: spill its KV state to host, release
        its blocks to the pool, park it on the resume line. The future
        stays pending (the caller notices nothing but latency), the
        request's remaining tokens stay in the admission backlog, and
        one re-install unit joins them — the resume line is real work
        the estimator must price."""
        r = self._slot_req[slot]
        r.artifact = self._extract_artifact(slot)
        self._free_slot(slot)           # blocks back to the pool
        r.slot = None                   # r.version KEPT: the resume
        #                                 must run under the params the
        #                                 rows were computed with
        #                                 (_gc_versions guards it)
        with self._work_lock:
            if r.work_counted:
                r.work_left += 1        # the resume-install unit
                self._work_tokens += 1
        self._resume_q.append(r)
        self.metrics.count("preempted")
        tr = self._tracer
        if tr.enabled:
            tr.instant("decode.preempt", cat="serve",
                       track=f"req-{r.req_id}", trace_id=r.req_id)

    def _gate_signature(self):
        """Everything the preempting memory gate's outcome depends on:
        pool occupancy (admit feasibility), total decode progress (the
        anti-thrash eligibility clock — a victim becomes preemptible by
        decoding), and pending depth (new work to scan). Identical
        signature => an identical rescan outcome, so the gate skips it
        (see _admit_pending). Deadline expiries and line sweeps shrink
        the depth; completions/evictions/preemptions move the pool."""
        return (self._pool.blocks_free, self._pool.blocks_in_use,
                self.metrics.count_value("tokens_out"),
                self._pending_depth())

    def _try_preempt_for(self, req):
        """Free blocks for a memory-blocked `req` by preempting ONE
        victim slot, or return False when policy/occupancy offer none.
        Victims are DECODE-PHASE slots whose class the brownout policy
        ranks strictly below the claimant's (`may_preempt`) AND that
        have decoded at least `_PREEMPT_MIN_PROGRESS` tokens since
        their last (re)start — the anti-thrash floor: without it a
        just-resumed victim is immediately eligible again, and a
        sustained interactive stream pins it in a spill/restore loop
        paying a full-panel round-trip per ~token. Among candidates
        the most-yielding class goes first and, within it, the slot
        holding the most blocks (fewest preemptions to free the
        claimant's demand). A prefilling slot is never a victim — its
        panel is half-written."""
        if not self._preempt_on or self._brownout is None:
            return False
        cands = []
        for s, r in enumerate(self._slot_req):
            if r is None or r.pf_next is not None or r.alloc is None:
                continue
            if len(r.generated) - r.progress_base \
                    < self._PREEMPT_MIN_PROGRESS:
                continue
            if not self._brownout.may_preempt(r.klass, req.klass):
                continue
            rank = self._brownout.classes.get(
                str(r.klass), self._brownout.default)[0]
            cands.append((rank, -len(r.alloc.ids), s))
        if not cands:
            return False
        self._preempt_slot(min(cands)[2])
        return True

    def _check_artifact(self, art):
        """Structural fit of an artifact against THIS server (the
        version tag is checked separately — structure says the bytes
        can land, the tag says they may)."""
        k0 = art.panels[0][0]
        hd = self._d_model // self._n_heads
        if art.block_size != self._block_size:
            raise KVStateError(
                f"artifact block_size {art.block_size} != server "
                f"block_size {self._block_size}")
        if (len(art.panels) != self._n_layers
                or k0.shape[1:] != (self._n_heads, hd)
                or k0.dtype != np.dtype(self._cache_dtype)):
            raise KVStateError(
                f"artifact panel [{k0.shape[0]}, {k0.shape[1]}, "
                f"{k0.shape[2]}] x {len(art.panels)} layers "
                f"({k0.dtype}) does not fit this server's cache "
                f"([rows, {self._n_heads}, {hd}] x {self._n_layers}, "
                f"{np.dtype(self._cache_dtype)})")
        if len(art.prompt) + art.max_new > self.max_len:
            raise KVStateError(
                f"artifact needs {len(art.prompt)} + {art.max_new} "
                f"rows; server max_len is {self.max_len}")

    def migrate_out(self, future, timeout=30.0):
        """Export a live request's KV state as a `RequestArtifact` and
        DROP it locally: the request identified by its submit()
        `future` is extracted between scheduling iterations (the serve
        thread performs the gather; this call blocks until it has), its
        blocks are released, and the local future fails with
        `RequestMigratedError` — the importing server's
        `migrate_in(artifact)` future carries the resumed stream,
        bit-identical to an uninterrupted run. Only decode-phase
        requests are migratable (a prefilling panel is half-written; a
        queued request has no KV state to move — just resubmit it)."""
        if not self._paged:
            raise ValueError("migrate_out requires paged=True")
        if not self._running:
            raise ServerClosedError("server is not running")
        reply = cf.Future()
        self._migrate_cmds.append((future, reply))
        try:        # nudge an idle-blocked loop (the priority-line
            self._q.put_nowait(_Wake())     # wake pattern)
        except queue.Full:
            pass
        return reply.result(timeout)

    def _service_migrations(self):
        """Serve-thread half of `migrate_out`: resolve each pending
        export command against the live slots (and the resume line — a
        PREEMPTED request already is its artifact)."""
        while self._migrate_cmds:
            fut, reply = self._migrate_cmds.popleft()
            try:
                art = self._migrate_out_now(fut)
            except BaseException as e:  # noqa: BLE001 — reply carries it
                reply.set_exception(e)
            else:
                reply.set_result(art)

    def _service_prefix_ops(self):
        """Serve-thread half of `prefix_export`/`prefix_adopt`: answer
        queued fleet-prefix-tier commands at the iteration boundary,
        bounded by a per-iteration BYTES budget — at least one command
        always runs (progress), but a burst of peer pulls spreads over
        iterations instead of stalling one (the tier is a goodput
        optimization; it must never cost the current batch a beat)."""
        spent = 0
        while self._prefix_cmds and (
                spent == 0 or spent < self._prefix_io_budget):
            verb, arg, max_bytes, reply = self._prefix_cmds.popleft()
            try:
                if verb == "export":
                    art = self._prefix_export_now(arg, max_bytes)
                    spent += art.nbytes if art is not None else 0
                    out = art
                else:
                    spent += arg.nbytes
                    out = self._prefix_adopt_now(arg)
            except BaseException as e:  # noqa: BLE001 — reply carries it
                if not reply.done():
                    reply.set_exception(e)
            else:
                if not reply.done():
                    reply.set_result(out)

    def _mark_migrate_out(self, r):
        """Instant marker closing the request's lane on THIS instance:
        in the merged fleet trace it reads as the spill point between
        'decode on A' and 'resume on B'."""
        tr = self._tracer
        if tr.enabled:
            tr.instant("serve.migrate_out", cat="serve",
                       track=f"req-{r.req_id}", trace_id=r.req_id,
                       origin=self.instance)

    def _migrate_out_now(self, fut):
        for s, r in enumerate(self._slot_req):
            if r is None or r.future is not fut:
                continue
            if r.pf_next is not None:
                raise KVStateError(
                    "request is still in chunked prefill; only "
                    "decode-phase requests are migratable")
            art = self._extract_artifact(s)
            _fail_future(r.future, RequestMigratedError(
                "request exported to another server"))
            self._free_slot(s)
            self._gc_versions()
            self.metrics.count("migrated_out")
            self._mark_migrate_out(r)
            return art
        for r in list(self._resume_q):
            if r.future is fut and r.artifact is not None:
                self._resume_q.remove(r)
                art = r.artifact
                r.artifact = None
                _fail_future(r.future, RequestMigratedError(
                    "request exported to another server"))
                self.metrics.count("migrated_out")
                self._mark_migrate_out(r)
                return art
        raise KVStateError(
            "request not found in a decode slot (completed, failed, "
            "still queued, or never admitted here)")

    def migrate_in(self, artifact, deadline_ms=None):
        """Adopt another server's exported `RequestArtifact`: returns a
        Future resolving to the FULL token list (prompt + every
        generated token, pre- and post-migration), exactly what the
        source's future would have resolved to uninterrupted. The
        artifact's param tag must match this server's newest version
        (`KVStateVersionError` otherwise — checked here AND re-checked
        at admission, so a hot swap racing the import still refuses
        stale rows); the request then parks on the resume line and is
        installed when blocks and a slot free up."""
        if not self._paged:
            raise ValueError("migrate_in requires paged=True")
        if not self._running:
            raise ServerClosedError("server is not running")
        art = artifact
        with self._swap_lock:
            vidx = len(self._versions) - 1
        art.require_tag(self._version_tag(vidx), what="migrated request")
        self._check_artifact(art)
        need = self._pool.blocks_needed(len(art.prompt) + art.max_new - 1)
        if need > self._n_blocks or need > self._nb_slot:
            self.metrics.count("shed_blocks")
            raise ServerOverloadedError(
                f"migrated request needs {need} KV blocks but the "
                f"server holds {min(self._n_blocks, self._nb_slot)} "
                f"(pool / per-slot table)")
        # the max_queue budget caps MIGRATED pending work too (the ONE
        # shared check — a rebalancer draining a failing replica into
        # this one hits the same backpressure bound ordinary submits do)
        self._shed_if_lines_full()
        self.metrics.count("received")
        now = time.monotonic()
        if deadline_ms is not None:
            dl = now + deadline_ms / 1e3
        else:
            dl = (now + self.default_deadline
                  if self.default_deadline is not None else None)
        req = _DecodeRequest(list(art.prompt), art.max_new, dl,
                             klass=art.klass)
        req.generated = list(art.generated)
        ctx = art.trace or {}
        if isinstance(ctx.get("trace_id"), str):
            # cross-process trace continuity: continue the ORIGIN's
            # `req-<id>` lane under the same trace id, so the merged
            # trace reads enqueue -> decode on A -> spill -> resume
            # here as ONE request timeline. Only NAMED instances mint
            # string ids ("i0-7") — those are fleet-unique by
            # construction. An UNNAMED origin's plain integer id could
            # collide with this server's own counter (both count from
            # 0), silently fusing two requests' lanes in this trace —
            # so it gets a fresh local id instead (continuity is a
            # fleet feature; name the instances to get it).
            req.req_id = ctx["trace_id"]
        else:
            req.req_id = next(self._req_ids)
        req.migrated = True
        if art.remaining <= 0:
            # fully-decoded artifact: nothing left to serve — resolve
            # immediately rather than park a no-op on the resume line
            req.future.set_result(list(art.prompt) + req.generated)
            return req.future
        req.artifact = art
        # resume-line work units: the remaining token budget plus one
        # re-install unit join the backlog NOW — the estimator prices
        # parked migrated work like any queued work
        req.work_left = art.remaining + 1
        with self._work_lock:
            self._work_tokens += req.work_left
            req.work_counted = True
        req.future.add_done_callback(
            lambda _f, r=req: self._retire_work(r))
        self._migrate_in_q.append(req)
        try:        # nudge an idle-blocked loop
            self._q.put_nowait(_Wake())
        except queue.Full:
            pass
        tr = self._tracer
        if tr.enabled:
            kw = {"trace_id": req.req_id}
            if ctx.get("origin") is not None:
                kw["migrated_from"] = ctx["origin"]
            tr.instant("serve.migrate_in", cat="serve",
                       track=f"req-{req.req_id}", **kw)
            tr.instant("serve.enqueue", cat="serve",
                       track=f"req-{req.req_id}", trace_id=req.req_id)
        if not self._running:
            _fail_future(req.future, ServerClosedError(
                "server stopped during migrate_in"))
            raise ServerClosedError("server stopped during migrate_in")
        return req.future

    def _install_panel(self, ids, panels, length, shared_len):
        """Install host panel rows through a block table: rows
        [shared_len, length) land at their table-mapped arena rows via
        the SAME donated install scatter prefill uses, at full table
        width — one compiled restore shape per server, shared by
        resume, migrate-in, and the prefix-cache restore."""
        import jax.numpy as jnp
        R = self._nb_slot * self._block_size
        tab = np.zeros((self._nb_slot,), np.int32)
        tab[:len(ids)] = ids
        dev = []
        for k, v in panels:
            kp = np.zeros((1, R) + k.shape[1:], k.dtype)
            vp = np.zeros((1, R) + v.shape[1:], v.dtype)
            kp[0, :k.shape[0]] = k
            vp[0, :v.shape[0]] = v
            dev.append((jnp.asarray(kp), jnp.asarray(vp)))
        self._cache = self._paged_install(
            self._cache, dev, jnp.asarray(tab),
            jnp.asarray(int(length), jnp.int32),
            jnp.asarray(int(shared_len), jnp.int32))

    def _count_restore_hits(self, alloc):
        """Prefix blocks this admission shares that came from a
        restored snapshot — the restart-warm-start proof counter."""
        if not self._pool.restored:
            return
        hits = sum(1 for b in alloc.ids[:alloc.n_shared]
                   if b in self._pool.restored)
        if hits:
            self.metrics.count("prefix_restore_hits", hits)

    def _admit_restored(self, req, slot, alloc, vidx):
        """Install a spilled/migrated request into `slot` from its
        artifact: block table + position + one install dispatch for
        the rows the prefix match did not already make resident.
        Shared FULL leading blocks were re-acquired by the pool
        (refcount++, never duplicated) and are skipped by the install's
        index gate; a partial-block ride materializes its CoW spare
        BEFORE the install (body comment — installing through a
        still-shared partial block would overwrite the cached owner's
        tail). The resumed stream is bit-identical: panel rows ARE the
        bits the uninterrupted run computed, and decode continues from
        the same (pos, last token) state."""
        art = req.artifact
        pos = art.pos
        if alloc.cow is not None:
            # a PARTIAL-tail ride must not be installed into: the
            # install below writes rows [resident, pos), and with the
            # shared partial block still in the table those rows would
            # land INSIDE it — overwriting the cached owner's tail that
            # other prompts still match. Swap the reserved CoW spare in
            # NOW; no device row-copy is needed (unlike the decode-path
            # CoW) because the artifact carries every row of that block
            # and the install writes them all — so the resident set
            # shrinks to the FULL shared blocks only.
            self._pool.cow(alloc)
        resident = alloc.n_shared * self._block_size
        self._btabs[slot, :] = 0
        self._btabs[slot, :len(alloc.ids)] = alloc.ids
        req.alloc = alloc
        with self._tracer.span("decode.restore", cat="serve",
                               track="server", trace_id=req.req_id,
                               slot=slot, rows=pos, shared=resident):
            self._install_panel(alloc.ids, art.panels, pos, resident)
        # only now are the request's own prompt blocks really filled —
        # commit them to the prefix index (same ordering rule as
        # prefill: a failed install must never leave garbage matchable)
        self._pool.commit(alloc)
        self._count_restore_hits(alloc)
        self._spend_work(req)           # the install unit
        self._pos = self._pos.at[slot].set(pos)
        self._tok[slot] = req.generated[-1]
        req.pf_next = None
        req.slot = slot
        req.version = vidx
        req.artifact = None             # host copy released
        req.progress_base = len(req.generated)  # anti-thrash floor
        req.t_last_tok = time.monotonic()
        self._slot_req[slot] = req
        if self._spec is not None:
            self._spec.draft.start(slot, list(req.prompt) + req.generated)
        self.metrics.count("migrated" if req.migrated else "resumed")

    def _admit_resume(self, slot):
        """Serve the RESUME LINE into `slot` (ahead of every queue —
        parked spilled work is the oldest admitted work in the house).
        Non-blocking: a resume head that cannot get its blocks leaves
        admission open for queue work (which may fit in less, or
        preempt its own victim) instead of head-of-line-blocking the
        door; it retries every iteration and has first claim on freed
        blocks. Returns True when the slot was filled."""
        while self._resume_q:
            req = self._resume_q[0]
            if req.future.done():       # cancelled / failed while parked
                self._resume_q.popleft()
                continue
            now = time.monotonic()
            if req.deadline is not None and now > req.deadline:
                self._resume_q.popleft()
                if _fail_future(req.future, DeadlineExceededError(
                        "deadline expired on the resume line")):
                    self._deadline_miss(req, now)
                continue
            art = req.artifact
            if req.version is not None:
                vidx = req.version      # in-process preemption: the
                #                         pinned version (GC-guarded)
            else:
                with self._swap_lock:   # migrated in: newest version,
                    vidx = len(self._versions) - 1      # tag re-checked
                try:
                    art.require_tag(self._version_tag(vidx),
                                    what="migrated request")
                except KVStateVersionError as e:
                    self._resume_q.popleft()
                    if _fail_future(req.future, e):
                        self.metrics.count("failed")
                    continue
            alloc = self._pool.admit(
                req.prompt, len(req.prompt) + req.max_new - 1,
                will_append=True, tag=vidx)
            if alloc is None:
                if not req.mem_blocked:
                    req.mem_blocked = True
                    self.metrics.count("blocked_on_memory")
                return False
            self._resume_q.popleft()
            try:
                self._admit_restored(req, slot, alloc, vidx)
            except BaseException as e:  # noqa: BLE001 — fail THIS req
                self._pool.release(alloc)
                _fail_future(req.future, e)
                self.metrics.count("failed")
                continue
            return True
        return False

    def save_prefix_cache(self, path=None):
        """Persist the prefix cache's resident blocks (the pool's
        LRU-cached tier) as a `PrefixCacheArtifact` under the NEWEST
        param version's tag. Only entries indexed under that version
        are saved — older versions' rows would be unreachable after a
        restart anyway (the in-process tag rule). Call on a STOPPED
        server (stop() does, when `prefix_cache_dir` is set); returns
        the artifact path, or None when there is nothing to save."""
        if not (self._paged and self._prefix_cache):
            raise ValueError("no paged prefix cache to save")
        if self._running or (self._thread is not None
                             and self._thread.is_alive()):
            raise KVStateError("save_prefix_cache needs a stopped "
                               "server (the serve thread owns the "
                               "arena while running)")
        path = path if path is not None else self._prefix_dir
        if path is None:
            raise ValueError("no path: pass one or construct with "
                             "prefix_cache_dir=")
        with self._swap_lock:
            vidx = len(self._versions) - 1
        entries = self._pool.cached_entries(tag=vidx)
        if not entries:
            # nothing saveable under the NEWEST version. A snapshot
            # already at the server's OWN prefix_cache_dir is then
            # STALE (earlier params or an earlier run) and must not
            # survive: left in place it would strand the next
            # constructor on a loud version refusal the server's own
            # lifecycle caused (e.g. hot-swapped then stopped before
            # any new-version prefix landed). Remove it so the next
            # start is a clean cold start. An EXPLICITLY passed foreign
            # path is never deleted — it may be another server's valid
            # snapshot; the loud refusal stays reserved for those.
            own = (self._prefix_dir is not None
                   and os.path.abspath(path)
                   == os.path.abspath(self._prefix_dir))
            if own and artifact_kind(path) == "prefix_cache":
                import shutil
                shutil.rmtree(path, ignore_errors=True)
            return None
        tag = self._version_tag(vidx)
        bs = self._block_size
        panels_by_bid = {}
        # batch extraction through the one compiled [NB]-table gather:
        # nb_slot blocks per dispatch, rows sliced apart on host
        import jax.numpy as jnp
        ids = [bid for bid, _ in entries]
        for at in range(0, len(ids), self._nb_slot):
            group = ids[at:at + self._nb_slot]
            tab = np.zeros((self._nb_slot,), np.int32)
            tab[:len(group)] = group
            panels = self._extract(self._cache, jnp.asarray(tab))
            panels = [(np.asarray(k), np.asarray(v)) for k, v in panels]
            for i, bid in enumerate(group):
                panels_by_bid[bid] = [
                    (k[i * bs:(i + 1) * bs].copy(),
                     v[i * bs:(i + 1) * bs].copy()) for k, v in panels]
        art = PrefixCacheArtifact(
            tag, bs, [(prefix, panels_by_bid[bid])
                      for bid, prefix in entries])
        self.metrics.count("spill_bytes", art.nbytes)
        out = art.save(path)
        log.info("saved %d prefix-cache blocks (%d bytes) under tag %s "
                 "at %s", len(entries), art.nbytes, tag, out)
        return out

    def restore_prefix_cache(self, path=None):
        """Adopt a saved `PrefixCacheArtifact` into the (fresh) pool:
        tag-checked against the newest param version FIRST —
        `KVStateVersionError` on mismatch, zero blocks adopted (the
        loud-refusal rule) — then every entry gets a block
        (parent-first), its rows installed before serving can match
        it. A pool too small for the whole snapshot adopts a prefix of
        it. Returns the number of blocks restored. Like the save twin,
        this needs a NOT-running server (the constructor calls it
        before start()): the serve thread owns the arena and the pool
        while serving, and an install racing a decode dispatch on the
        donated buffers corrupts both."""
        if not (self._paged and self._prefix_cache):
            raise ValueError("no paged prefix cache to restore into")
        if self._running or (self._thread is not None
                             and self._thread.is_alive()):
            raise KVStateError("restore_prefix_cache needs a stopped "
                               "server (the serve thread owns the "
                               "arena while running)")
        path = path if path is not None else self._prefix_dir
        if path is None:
            raise ValueError("no path: pass one or construct with "
                             "prefix_cache_dir=")
        art = PrefixCacheArtifact.load(path)
        with self._swap_lock:
            vidx = len(self._versions) - 1
        art.require_tag(self._version_tag(vidx),
                        what="prefix-cache snapshot")
        if art.entries:
            self._check_artifact_panels(art)
        adopted = []                    # (bid, panels) in adopt order
        for prefix, panels in art.entries:
            bid = self._pool.adopt((vidx, prefix))
            if bid is None:
                continue
            adopted.append((bid, panels))
        bs = self._block_size
        for at in range(0, len(adopted), self._nb_slot):
            group = adopted[at:at + self._nb_slot]
            ids = [bid for bid, _ in group]
            rows = [(np.concatenate([p[li][0] for _, p in group]),
                     np.concatenate([p[li][1] for _, p in group]))
                    for li in range(self._n_layers)]
            self._install_panel(ids, rows, len(ids) * bs, 0)
        if adopted:
            log.info("restored %d prefix-cache blocks under tag %s",
                     len(adopted), art.tag)
        return len(adopted)

    def prefix_export(self, key, max_bytes=None, timeout=30.0):
        """Export the resident prefix-cache chain covering `key` (the
        leading block-aligned prompt tokens) as a `PrefixCacheArtifact`
        under the NEWEST param version's tag — the fleet prefix tier's
        SOURCE seam (serving/wire.py OP_PREFIX_PULL): a peer missing a
        hot prefix adopts this instead of recomputing it. Valid on a
        RUNNING server: the serve thread performs the gather between
        scheduling iterations (this call blocks until it has), and
        indexed rows are immutable once committed, so live sharers are
        unaffected. Non-destructive — the blocks stay resident here.
        `max_bytes` truncates the chain parent-first (a partial chain
        is still matchable from the front). Returns None when nothing
        indexed under the newest version covers `key`."""
        if not (self._paged and self._prefix_cache):
            raise ValueError("prefix_export requires paged=True with "
                             "prefix_cache=True")
        if not self._running:
            raise ServerClosedError("server is not running")
        reply = cf.Future()
        self._prefix_cmds.append(("export", tuple(key), max_bytes,
                                  reply))
        try:        # nudge an idle-blocked loop
            self._q.put_nowait(_Wake())
        except queue.Full:
            pass
        return reply.result(timeout)

    def prefix_adopt(self, artifact, timeout=30.0):
        """Adopt a peer's exported prefix chain into the running pool —
        the fleet prefix tier's SINK seam. Tag-checked FIRST against
        the newest param version (`KVStateVersionError` on mismatch,
        zero blocks adopted, `prefix_pull_refused` counted — the caller
        degrades to cold compute); adoption never evicts resident state
        (a full pool adopts a prefix of the chain). Returns the number
        of blocks adopted; counts `prefix_pull_hits` (blocks) and
        `prefix_pull_bytes` for the fleet books."""
        if not (self._paged and self._prefix_cache):
            raise ValueError("prefix_adopt requires paged=True with "
                             "prefix_cache=True")
        if not self._running:
            raise ServerClosedError("server is not running")
        reply = cf.Future()
        self._prefix_cmds.append(("adopt", artifact, None, reply))
        try:        # nudge an idle-blocked loop
            self._q.put_nowait(_Wake())
        except queue.Full:
            pass
        return reply.result(timeout)

    def _prefix_export_now(self, key, max_bytes):
        """Serve-thread half of `prefix_export`: walk the pool's index
        chain under the newest version and pull the rows to host
        through the SAME batched [NB]-table gather the persistent
        prefix cache uses."""
        with self._swap_lock:
            vidx = len(self._versions) - 1
        chain = self._pool.indexed_chain(key, tag=vidx)
        bs = self._block_size
        if max_bytes is not None and chain:
            # fixed per-block payload: truncate parent-first BEFORE
            # extracting (no device work for bytes that won't ship)
            per_block = (2 * self._n_layers * bs * self._n_heads
                         * (self._d_model // self._n_heads)
                         * np.dtype(self._cache_dtype).itemsize)
            chain = chain[:int(max_bytes) // per_block]
        if not chain:
            return None
        import jax.numpy as jnp
        ids = [bid for bid, _ in chain]
        panels_by_bid = {}
        for at in range(0, len(ids), self._nb_slot):
            group = ids[at:at + self._nb_slot]
            tab = np.zeros((self._nb_slot,), np.int32)
            tab[:len(group)] = group
            panels = self._extract(self._cache, jnp.asarray(tab))
            panels = [(np.asarray(k), np.asarray(v))
                      for k, v in panels]
            for i, bid in enumerate(group):
                panels_by_bid[bid] = [
                    (k[i * bs:(i + 1) * bs].copy(),
                     v[i * bs:(i + 1) * bs].copy()) for k, v in panels]
        return PrefixCacheArtifact(
            self._version_tag(vidx), bs,
            [(prefix, panels_by_bid[bid]) for bid, prefix in chain])

    def _prefix_adopt_now(self, art):
        """Serve-thread half of `prefix_adopt`: `restore_prefix_cache`
        at the iteration boundary — tag check FIRST (the loud-refusal
        rule, counted), then adopt + grouped install, parent-first."""
        with self._swap_lock:
            vidx = len(self._versions) - 1
        try:
            art.require_tag(self._version_tag(vidx),
                            what="pulled prefix blocks")
        except KVStateVersionError:
            self.metrics.count("prefix_pull_refused")
            raise
        if art.entries:
            self._check_artifact_panels(art)
        adopted = []
        nbytes = 0
        for prefix, panels in art.entries:
            bid = self._pool.adopt((vidx, prefix))
            if bid is None:
                continue
            adopted.append((bid, panels))
            nbytes += sum(k.nbytes + v.nbytes for k, v in panels)
        bs = self._block_size
        for at in range(0, len(adopted), self._nb_slot):
            group = adopted[at:at + self._nb_slot]
            ids = [bid for bid, _ in group]
            rows = [(np.concatenate([p[li][0] for _, p in group]),
                     np.concatenate([p[li][1] for _, p in group]))
                    for li in range(self._n_layers)]
            self._install_panel(ids, rows, len(ids) * bs, 0)
        if adopted:
            self.metrics.count("prefix_pull_hits", len(adopted))
            self.metrics.count("prefix_pull_bytes", nbytes)
        return len(adopted)

    def _check_artifact_panels(self, art):
        """Prefix-cache twin of `_check_artifact` (no request fields)."""
        k0 = art.entries[0][1][0][0]
        hd = self._d_model // self._n_heads
        if (art.block_size != self._block_size
                or len(art.entries[0][1]) != self._n_layers
                or k0.shape[1:] != (self._n_heads, hd)
                or k0.dtype != np.dtype(self._cache_dtype)):
            raise KVStateError(
                f"prefix-cache snapshot (block_size {art.block_size}, "
                f"{len(art.entries[0][1])} layers, rows x "
                f"{k0.shape[1:]} {k0.dtype}) does not fit this server "
                f"(block_size {self._block_size}, {self._n_layers} "
                f"layers, rows x ({self._n_heads}, {hd}) "
                f"{np.dtype(self._cache_dtype)})")

    # -- scheduler internals -------------------------------------------
    def _complete(self, req, t_now):
        """Resolve one finished request: future, latency + SLO metrics,
        the request-timeline span, and the flight-recorder feed. ONE
        implementation for the three completion sites (prefill-only,
        plain iteration, speculative iteration) so SLO accounting cannot
        drift between them."""
        if not _resolve_future(req.future,
                               list(req.prompt) + req.generated):
            return
        total_ms = (t_now - req.t_submit) * 1e3
        self.metrics.record_request(
            total_ms, tokens=len(req.generated),
            deadline_met=(None if req.deadline is None
                          else t_now <= req.deadline))
        if req.deadline is not None:
            self._slo_recent.append(1 if t_now <= req.deadline else 0)
        self._admission_outcome(req, t_now, completed=True)
        tr = self._tracer
        if tr.enabled:
            t0 = int(req.t_submit * 1e9)
            tr.emit("serve.request", t0, int(total_ms * 1e6), cat="serve",
                    track=f"req-{req.req_id}", trace_id=req.req_id,
                    args={"tokens": len(req.generated)})
        if self._flight is not None:
            self._flight.observe(total_ms)

    def _reset_device_state(self):
        """Fresh slot state: the KV cache, per-slot positions/tokens, and
        host-side occupancy. Called at construction and after a decode
        dispatch fails terminally (the donated cache/pos buffers may have
        been consumed by the failed call — they cannot be trusted)."""
        import jax.numpy as jnp

        from ..models.zoo.transformer import (init_kv_cache,
                                              init_paged_kv_cache)
        if self._paged:
            from .kvpool import BlockPool
            self._cache = init_paged_kv_cache(
                self._n_layers, self._n_blocks, self._block_size,
                self._d_model, self._n_heads, self._cache_dtype)
            # the pool dies with the arena: every allocation referenced
            # rows in buffers that no longer exist
            self._pool = BlockPool(self._n_blocks, self._block_size,
                                   prefix_cache=self._prefix_cache)
            self._btabs = np.zeros((self.slots, self._nb_slot), np.int32)
        else:
            self._cache = init_kv_cache(self._n_layers, self.slots,
                                        self.max_len, self._d_model,
                                        self._n_heads, self._cache_dtype)
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        # tok is HOST state uploaded per dispatch (like active/btabs):
        # chunk-prefill transitions and decode iterations both write
        # per-slot entries, and a device-side array rebuilt from one
        # iteration's live set would silently zero the slots the other
        # path just set
        self._tok = np.zeros((self.slots,), np.int32)
        self._slot_req = [None] * self.slots     # host-side occupancy
        spec = getattr(self, "_spec", None)      # unset on first call
        if spec is not None:
            for s in range(self.slots):          # idempotent stops
                spec.draft.stop(s)

    @property
    def prefill_programs(self):
        """bucket -> compiled prefill program (compile-cache pin)."""
        return dict(self._prefills)

    def _prompt_bucket(self, n):
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def _admit(self, req, slot, alloc=None, version=None):
        """Prefill `req`'s prompt and install it into `slot` (paged
        mode: through `alloc`'s block table — a pure prefill dispatch
        plus the donated install scatter on success). `version` is the
        (vidx, aux, blocks) the PAGED caller
        already bound when it tagged the pool admission — prefill must
        run under exactly the params the prefix match was tagged with,
        or a swap racing the admission could share old-version rows
        into a new-version stream."""
        import jax.numpy as jnp
        tr = self._tracer
        if tr.enabled:
            # queue wait ends at ADMISSION here (a decode request's
            # "batch formation" is winning a slot)
            t0 = int(req.t_submit * 1e9)
            tr.emit("serve.queue_wait", t0, time.monotonic_ns() - t0,
                    cat="serve", track=f"req-{req.req_id}",
                    trace_id=req.req_id)
        if version is not None:
            vidx, aux, blocks = version
        else:
            with self._swap_lock:   # version index + params read atomically
                vidx = len(self._versions) - 1
                aux, blocks = self._versions[vidx]
        if self._chunk is not None and len(req.prompt) > self._chunk:
            # chunked prefill: NO monolithic prompt dispatch here — the
            # request enters its slot in the PREFILL phase and the
            # scheduler advances it C rows per iteration
            # (_chunk_iteration), interleaved with everyone's decode.
            # The CHUNK SIZING RULE: only prompts LONGER than one chunk
            # take this path — a prompt that fits in one chunk already
            # IS a chunk-sized stall, and the one-shot bucket program
            # below runs it at [1, Pb] instead of the chunk program's
            # [slots, C] (the S-wide chunk dispatch computes every slot
            # unconditionally, so routing short prompts through it
            # would multiply the fleet-dominant traffic's prefill
            # compute by the slot count for zero head-of-line benefit).
            self._admit_chunked(req, slot, alloc, vidx)
            return
        bucket = self._prompt_bucket(len(req.prompt))
        prog = self._prefills.get(bucket)
        if prog is None:
            prog = self._prefills[bucket] = self._make_prefill()
            log.info("compiled prefill program for prompt bucket %d",
                     bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(req.prompt)] = req.prompt

        def dispatch():
            if self._injector is not None:
                self._injector.fire("serve.batch")
            return prog(aux, blocks, jnp.asarray(padded),
                        jnp.asarray(len(req.prompt), jnp.int32))

        with self._tracer.span("decode.prefill", cat="serve",
                               track="server", trace_id=req.req_id,
                               bucket=bucket, slot=slot):
            if self._retry is not None:
                logits, rows = self._retry.call(
                    dispatch,
                    on_retry=lambda a, e, d: self.metrics.count("retries"))
            else:
                logits, rows = dispatch()
        if self._paged:
            # `rows` are the prompt's k/v panels: scatter them to their
            # block-table rows in the DONATED install (arena aliased in
            # place — a prefill failure above leaves it untouched). Only
            # now are the prompt blocks really filled, so only now may
            # they enter the prefix index — commit() BEFORE this point
            # would let a failed prefill leave garbage blocks matchable
            tab = np.zeros((self._nb_slot,), np.int32)
            tab[:len(alloc.ids)] = alloc.ids
            self._cache = self._paged_install(
                self._cache, rows, jnp.asarray(tab),
                jnp.asarray(len(req.prompt), jnp.int32),
                jnp.asarray(alloc.shared_rows, jnp.int32))
            self._pool.commit(alloc)
            self.metrics.count("prefix_rows_total", len(req.prompt))
            if alloc.shared_rows:
                self.metrics.count("prefix_rows_hit", alloc.shared_rows)
        first = int(np.argmax(np.asarray(logits)[0]))
        req.generated.append(first)
        # TTFT closes HERE: prefill's argmax IS the first generated
        # token, whether or not the request goes on to occupy a slot
        req.t_last_tok = time.monotonic()
        self.metrics.record_ttft((req.t_last_tok - req.t_submit) * 1e3)
        self._spend_work(req, 2)    # the prefill unit + the first token
        if len(req.generated) >= req.max_new:
            # one-token request: done at prefill, never occupies a slot
            # (paged: its blocks release immediately — and a shared
            # partial block it rode needed no CoW, the zero-copy case)
            self._complete(req, time.monotonic())
            if self._paged:
                self._pool.release(alloc)
            return
        if self._paged:
            req.alloc = alloc
            self._btabs[slot, :] = 0
            self._btabs[slot, :len(alloc.ids)] = alloc.ids
        else:
            self._cache = self._install(self._cache, rows, slot)
        self._pos = self._pos.at[slot].set(len(req.prompt))
        self._tok[slot] = first
        req.slot = slot
        req.version = vidx
        self._slot_req[slot] = req
        if self._spec is not None:
            # draft stream keyed by slot: full context so far (slot reuse
            # is safe — start() resets the key, _free_slot stops it)
            self._spec.draft.start(slot, list(req.prompt) + req.generated)

    def _admit_chunked(self, req, slot, alloc, vidx):
        """Install `req` into `slot` in the PREFILL phase: block table /
        position state only, zero dispatches. Paged mode starts the
        chunk cursor past any resident shared prefix — a prefix-cache
        hit now saves the prompt COMPUTE, not just the install — but
        always re-runs at least the final prompt row, whose argmax IS
        the first generated token (write-gated below `pf_wfrom`, so
        recomputed shared rows are never re-installed and a shared
        partial block is never touched)."""
        plen = len(req.prompt)
        if self._paged:
            self._btabs[slot, :] = 0
            self._btabs[slot, :len(alloc.ids)] = alloc.ids
            req.alloc = alloc
            start = min(alloc.shared_rows, plen - 1)
            req.pf_wfrom = alloc.shared_rows
        else:
            start = 0
            req.pf_wfrom = 0
        req.pf_next = start
        # prefix hits skip leading chunks: retire their work units NOW,
        # or they would sit in the admission backlog as phantoms until
        # the future resolves, over-predicting every later request.
        # Retirement is against the units QUOTED at submit (a priority
        # hit was quoted 1 chunk already, so a surviving hit retires
        # nothing here; an evaporated hit's extra chunks clamp against
        # the request's remaining budget in _spend_work)
        chunks_left = -(-(plen - start) // self._chunk)
        self._spend_work(req, max(0, req.pf_quoted - chunks_left))
        self._pos = self._pos.at[slot].set(start)
        self._tok[slot] = 0
        req.slot = slot
        req.version = vidx
        self._slot_req[slot] = req

    def _next_request(self, wait):
        """Head of the admission line: memory-blocked requests first
        (FIFO — a small late request must not starve a big early one),
        then the prefix-hit PRIORITY line (a full-prefix hit costs one
        chunk of prefill, so it overtakes cold prompts by policy —
        counted `admitted_prefix_priority` when it actually overtakes
        queued work), then the submit queue, then the brownout-DEFERRED
        line — served only when the primary queue is empty, which is
        the policy: deferred classes yield until pressure drops. The
        blocking `wait` engages only when every line is empty (the
        idle sleep)."""
        if self._mem_wait:
            return self._mem_wait.popleft()
        # discard wake sentinels at the queue head FIRST (safe: this
        # loop is the queue's only consumer; producers only append):
        # a sentinel is a nudge, not work — left in place it would
        # read as queued work to the overtake flag below and spend the
        # anti-starvation fairness turn on a no-op
        while True:
            try:
                if not isinstance(self._q.queue[0], _Wake):
                    break
                self._q.get_nowait()
            except (IndexError, queue.Empty):
                break
        # anti-starvation bound: after _PRIO_BURST consecutive genuine
        # overtakes, the primary head takes one turn — sustained hit
        # traffic degrades cold prompts' position, never parks them
        # forever (the deferred line's reciprocal guarantee)
        if not (self._prio_streak >= self._PRIO_BURST
                and not self._q.empty()):
            r = self._pop_prio()
            if r is not None:
                if r.prio_overtook:
                    self._prio_streak += 1
                return r
        try:
            r = self._q.get_nowait()
        except queue.Empty:
            pass
        else:
            self._prio_streak = 0
            return r
        if self._defer_q:
            try:
                r = self._defer_q.popleft()
            except IndexError:          # raced a concurrent drain
                return None
            # leaving the deferred line: its work joins the backlog now
            with self._work_lock:
                if not r.work_counted:
                    self._work_tokens += r.work_left
                    r.work_counted = True
            return r
        if wait:
            # the idle sleep. Priority submits that land while the get
            # blocks push a `_Wake` sentinel through the queue (see
            # `_enqueue_priority`): the get returns it, the caller
            # discards its done future, and the next `_next_request`
            # pops the priority line first — no polling, no timeout
            # eaten by the parked request.
            try:
                return self._q.get(timeout=wait)
            except queue.Empty:
                return None
        return None

    def _pop_prio(self):
        """Pop the priority line's head (None when empty or raced),
        flagging whether it genuinely overtook queued work — the flag
        is counted only when the request actually ADMITS, so a
        deadline-expired or caller-cancelled pop never reports an
        overtake that did not happen."""
        if not self._prio_q:
            return None
        try:
            r = self._prio_q.popleft()
        except IndexError:              # raced a concurrent drain
            return None
        r.prio_overtook = not self._q.empty()
        return r

    def _admit_pending(self, timeout=0.0):
        """Fill free slots from the queue. `timeout` blocks on the FIRST
        get only — the idle loop's way of waiting for work on the queue
        itself instead of busy-polling at the 1 ms decode tick. Paged
        mode adds the MEMORY gate: a request that cannot get its blocks
        parks at the head of the line (`blocked_on_memory` counted once)
        and admission stops until completions free blocks — EXCEPT with
        `preempt=True`, where a blocked request must not wall off the
        line behind it: a claimant stuck behind a blocked lower-class
        head would never reach its preemption chance (head-of-line
        priority inversion), so the preempting gate keeps scanning —
        blocked requests collect in arrival order and re-park at the
        FRONT of the memory line (keeping first claim on freed blocks)
        while later requests get their own admit-or-preempt attempt."""
        if not self._running and not self._drain_on_stop:
            # fail-fast stop: queued requests must NOT be admitted into
            # freed slots — the loop's final drain fails them once the
            # busy slots finish. The memory-wait AND deferred lines are
            # failed HERE, not at loop exit: parked requests count as
            # _busy(), so leaving either parked would keep the loop
            # alive (and their futures unresolved) forever once the
            # slots drain.
            self._fail_parked(ServerClosedError("server stopped"))
            return
        free = [s for s in range(self.slots) if self._slot_req[s] is None]
        if self._static and len(free) < self.slots:
            return      # gang scheduling: wait for the whole batch
        if self._preempt_on and self._gate_key is not None \
                and self._gate_key == self._gate_signature():
            # the last full preempting-gate scan admitted nothing, and
            # NOTHING it depends on has changed since (pool occupancy,
            # decode progress — the anti-thrash eligibility input —
            # or pending depth): re-running the O(pending x slots)
            # scan every ~1 ms tick would tax the serve thread exactly
            # when the machine is most loaded, for an identical outcome
            return
        wait = float(timeout)
        blocked = []    # memory-blocked pops, in arrival order
        admitted = False    # anything placed into a slot this call?
        try:
            for s in free:
                if self._paged and self._admit_resume(s):
                    # spilled/migrated work re-enters ahead of every
                    # queue
                    admitted = True
                    continue
                req, alloc = None, None
                while req is None:
                    req = self._next_request(wait)
                    wait = 0.0
                    if req is None:
                        return
                    if req.future.done():   # failed by raced submit/stop
                        req = None
                    elif req.deadline is not None and \
                            time.monotonic() > req.deadline:
                        if _fail_future(req.future, DeadlineExceededError(
                                "deadline expired before prefill")):
                            self._deadline_miss(req, time.monotonic())
                        req = None
                    elif self._paged:
                        # admission gated by FREE BLOCKS, not free
                        # slots: reserve everything the request will
                        # ever write (prompt + decode rows, minus any
                        # shared prefix). The param version is bound
                        # HERE, before the prefix match: the match is
                        # tagged with it and the prefill below runs
                        # under the same params, so a swap racing this
                        # admission cannot share old-version rows into
                        # a new-version stream.
                        with self._swap_lock:
                            vidx = len(self._versions) - 1
                            aux, blocks = self._versions[vidx]
                        version = (vidx, aux, blocks)
                        alloc = self._pool.admit(
                            req.prompt, len(req.prompt) + req.max_new - 1,
                            will_append=req.max_new > 1, tag=vidx)
                        # PREEMPTION (module docstring): a claimant
                        # whose class outranks a live slot's takes that
                        # slot's blocks — victims spill to host one at
                        # a time until the claimant fits or policy runs
                        # out of victims
                        while alloc is None and \
                                self._try_preempt_for(req):
                            alloc = self._pool.admit(
                                req.prompt,
                                len(req.prompt) + req.max_new - 1,
                                will_append=req.max_new > 1, tag=vidx)
                        if alloc is None:
                            if not req.mem_blocked:
                                req.mem_blocked = True
                                self.metrics.count("blocked_on_memory")
                            blocked.append(req)
                            if not self._preempt_on:
                                return      # FIFO gate: stop admission
                            req = None      # preempting gate: scan on
                try:
                    self._admit(req, s, alloc,
                                version=version if self._paged else None)
                except BaseException as e:  # noqa: BLE001 — fail THIS
                    if alloc is not None:   # request
                        self._pool.release(alloc)
                    _fail_future(req.future, e)
                    self.metrics.count("failed")
                else:
                    admitted = True
                    if alloc is not None:
                        self._count_restore_hits(alloc)
                    if req.prio_overtook:
                        # a REAL reordered admission: the request left
                        # the priority line past queued work and
                        # prefilled
                        req.prio_overtook = False
                        self.metrics.count("admitted_prefix_priority")
        finally:
            if blocked:
                # re-park at the FRONT in arrival order: first claim on
                # freed blocks stays with the oldest blocked request
                self._mem_wait.extendleft(reversed(blocked))
            if self._preempt_on:
                # arm the rescan guard only after a FULLY blocked scan;
                # any admission/preemption changed the inputs anyway
                self._gate_key = (self._gate_signature()
                                  if blocked and not admitted else None)

    def _free_slot(self, slot):
        """Release `slot`'s host-side occupancy (and its draft stream,
        and — paged — its block-table allocation back to the pool).
        Device rows/pos are left stale on purpose: the next admission
        resets pos and decode overwrites rows before attending (the
        dead-row contract); a freed slot's stale block table is inert
        because inactive slots' writes are index-dropped."""
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        if self._paged and req is not None and req.alloc is not None:
            self._pool.release(req.alloc)
            req.alloc = None
            self._btabs[slot, :] = 0
        if self._spec is not None:
            self._spec.draft.stop(slot)

    def _sweep_line(self, dq, msg, now, thrash=True):
        """THE deadline sweep for every parked FIFO line (memory gate,
        priority line, deferred line — waiting anywhere is queue wait):
        one rotation skipping already-resolved futures and failing
        expired ones through the shared `_deadline_miss` bookkeeping.
        Keepers return to the FRONT in order (deque ops are each
        atomic, so a submit appending concurrently is safe and lands
        BEHIND them — the sweep preserves line-FIFO fairness instead
        of leapfrogging old requests). `thrash=False` is the deferred
        line's flag: a class starved by brownout POLICY expiring is
        not evidence of overload, so it must not tighten admission."""
        keep = []
        for _ in range(len(dq)):
            try:
                r = dq.popleft()
            except IndexError:
                break
            if r.future.done():
                continue
            if r.deadline is not None and now > r.deadline:
                if _fail_future(r.future, DeadlineExceededError(msg)):
                    self._deadline_miss(r, now, thrash=thrash)
            else:
                keep.append(r)
        dq.extendleft(reversed(keep))

    def _evict_expired(self):
        """Mid-decode deadline enforcement: a slot whose request deadline
        has passed is evicted BETWEEN iterations — future fails with
        DeadlineExceededError, the shed is counted, and the slot frees
        THIS iteration (the following `_admit_pending` can refill it).
        Admission-time shedding (`_admit_pending`) only protects requests
        that expire in the queue; this protects the slots themselves from
        requests whose token budget outlives their latency budget."""
        now = time.monotonic()
        self._sweep_line(self._mem_wait,
                         "deadline expired while blocked on KV blocks",
                         now)
        self._sweep_line(self._prio_q,
                         "deadline expired in the priority line", now)
        self._sweep_line(self._defer_q,
                         "deadline expired while brownout-deferred",
                         now, thrash=False)
        self._sweep_line(self._resume_q,
                         "deadline expired on the resume line", now)
        evicted = False
        for s, r in enumerate(self._slot_req):
            if r is None or r.deadline is None or now <= r.deadline:
                continue
            mid_decode = r.pf_next is None
            phase = (f"mid-decode after {len(r.generated)} tokens"
                     if mid_decode else "during chunked prefill")
            if _fail_future(r.future, DeadlineExceededError(
                    f"deadline expired {phase}")):
                if mid_decode:
                    # prefill-phase evictions stay OUT of this counter:
                    # it is the decode-work-thrown-away signal the
                    # overload A/B judges the admission predictor on
                    self.metrics.count("evicted_mid_decode")
                self._deadline_miss(r, now)
            self._free_slot(s)
            evicted = True
        if evicted:
            self._gc_versions()

    def _materialize_cow(self, live):
        """Lazy copy-on-write, at exactly the FIRST divergent append: a
        live slot whose next write lands in a block it still SHARES gets
        its private copy now — the spare was reserved at admission, so
        this can never fail for lack of blocks. One small device copy
        per CoW event (per REQUEST, not per token — the per-token
        dispatch count is pinned unchanged by tests/test_paged.py)."""
        import jax.numpy as jnp
        for s, r in live:
            if r.alloc is None or r.alloc.cow is None:
                continue
            src, dst = self._pool.cow(r.alloc)
            self._btabs[s, :len(r.alloc.ids)] = r.alloc.ids
            with self._tracer.span("decode.cow", cat="serve",
                                   track="server", src=src, dst=dst):
                self._cache = self._cow_copy(
                    self._cache, jnp.asarray(src, jnp.int32),
                    jnp.asarray(dst, jnp.int32))
            self.metrics.count("cow_copies")

    def _fail_parked(self, exc):
        """Fail everything parked OUTSIDE the submit queue: the paged
        memory-wait line, the prefix-hit priority line, and the
        brownout-deferred line (all count as _busy(), so all must
        resolve before a stop may exit — the PR 8 memory-waiter
        livelock pin, extended to every parked line)."""
        for dq in (self._mem_wait, self._prio_q, self._defer_q,
                   self._resume_q, self._migrate_in_q):
            while dq:
                try:
                    r = dq.popleft()
                except IndexError:      # raced a concurrent drain
                    break
                if _fail_future(r.future, exc):
                    self.metrics.count("failed")
        while self._migrate_cmds:
            try:
                _, reply = self._migrate_cmds.popleft()
            except IndexError:
                break
            if not reply.done():
                reply.set_exception(exc)
        while self._prefix_cmds:
            try:
                *_ignored, reply = self._prefix_cmds.popleft()
            except IndexError:
                break
            if not reply.done():
                reply.set_exception(exc)
        while self._drain_cmds:
            try:
                _, reply = self._drain_cmds.popleft()
            except IndexError:
                break
            if not reply.done():
                reply.set_exception(exc)

    def _fail_queued(self, exc):
        """Queued = the submit queue, the paged memory-wait line, AND
        the brownout-deferred line. On a KILLED server the named crash
        error wins whatever exception the exiting loop passed (the
        loop may notice `_running` dropped before it notices
        `_killed` — a queued caller must still see the crash, not a
        clean shutdown)."""
        if self._killed:
            exc = ReplicaDeadError(f"replica {self.instance!r} crashed")
        self._fail_parked(exc)
        super()._fail_queued(exc)

    def _observe_rate(self, tokens, dt, active=0):
        """Feed one scheduling iteration into the admission estimator
        and publish the live capacity estimate (no-op without admission
        control)."""
        if self._admission is None:
            return
        est = self._admission.estimator
        est.observe(tokens, dt, active)
        tps = est.tokens_per_second
        if tps is not None:
            self.metrics.record_service_rate(tps)

    def _note_iter_time(self, dt):
        """Fold one decode iteration's wall time into the EWMA the
        fused deadline clamp divides by (`_fused_window_ok`). Fed by
        the plain path per iteration and by the fused path per window
        (window wall / K) — so the estimate tracks the PER-ITERATION
        cost in both modes and the clamp's horizon arithmetic stays in
        one unit."""
        a = 0.2
        self._iter_ewma = (dt if self._iter_ewma is None
                           else a * dt + (1 - a) * self._iter_ewma)

    def _chunk_iteration(self, pf):
        """Advance every PREFILLING slot one chunk (C prompt rows): one
        chunk dispatch per live param version, active mask restricted to
        that version's prefilling slots. A slot whose FINAL chunk lands
        transitions to the decode phase: the last real row's argmax is
        the first generated token (TTFT closes here, exactly as the
        one-shot prefill's argmax closes it), the paged prompt blocks
        commit to the prefix index only now (a failed chunk must never
        leave garbage blocks matchable), and a one-token request
        completes without ever decoding. Chunk dispatches count
        `chunk_dispatches`, not `dispatches` — prefill work has never
        been in the per-token dispatch counters."""
        import jax.numpy as jnp
        C = self._chunk
        tr = self._tracer
        done_any = False
        for v in sorted({r.version for _, r in pf}):
            pf_v = [(s, r) for s, r in pf if r.version == v]
            active = np.zeros((self.slots,), bool)
            toks = np.zeros((self.slots, C), np.int32)
            nrows = np.zeros((self.slots,), np.int32)
            wfrom = np.zeros((self.slots,), np.int32)
            wto = np.zeros((self.slots,), np.int32)
            for s, r in pf_v:
                active[s] = True
                n = min(C, len(r.prompt) - r.pf_next)
                nrows[s] = n
                toks[s, :n] = r.prompt[r.pf_next:r.pf_next + n]
                wfrom[s] = r.pf_wfrom
                wto[s] = len(r.prompt)
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                if self._paged:
                    return self._chunk_step(
                        aux, blocks, self._cache,
                        jnp.asarray(self._btabs), self._pos,
                        jnp.asarray(toks), jnp.asarray(nrows),
                        jnp.asarray(active), jnp.asarray(wfrom),
                        jnp.asarray(wto))
                return self._chunk_step(
                    aux, blocks, self._cache, self._pos,
                    jnp.asarray(toks), jnp.asarray(nrows),
                    jnp.asarray(active))

            # same donated-buffer retry contract as the decode step: the
            # injector site sits BEFORE the compiled call; a failure
            # inside it is terminal here (loop resets device state)
            t0 = time.monotonic_ns() if tr.enabled else None
            if self._retry is not None:
                nxt, self._cache, self._pos = self._retry.call(
                    dispatch,
                    on_retry=lambda a, e, d: self.metrics.count(
                        "retries"))
            else:
                nxt, self._cache, self._pos = dispatch()
            self.metrics.count("chunk_dispatches")
            for s, r in pf_v:
                self._spend_work(r)     # one chunk = one work unit
            nxt = np.asarray(nxt)
            if t0 is not None:
                # one prefill span per PREFILLING REQUEST over the
                # shared chunk window, on its own request lane:
                # decompose attributes the window to each prefilled
                # request's prefill_ms, while co-resident decoders still
                # see it as sched_gap — the before/after head-of-line
                # metric chunking exists to shrink
                dur = time.monotonic_ns() - t0
                for s, r in pf_v:
                    tr.emit("decode.prefill", t0, dur, cat="serve",
                            track=f"req-{r.req_id}", trace_id=r.req_id,
                            args={"chunk": int(nrows[s]), "slot": s})
            t_now = time.monotonic()
            for s, r in pf_v:
                r.pf_next += int(nrows[s])
                if r.pf_next < len(r.prompt):
                    continue
                r.pf_next = None        # final chunk: decode phase now
                if self._paged:
                    self._pool.commit(r.alloc)
                    self.metrics.count("prefix_rows_total",
                                       len(r.prompt))
                    if r.alloc.shared_rows:
                        self.metrics.count("prefix_rows_hit",
                                           r.alloc.shared_rows)
                first = int(nxt[s, int(nrows[s]) - 1])
                r.generated.append(first)
                r.t_last_tok = t_now
                self.metrics.record_ttft(
                    (r.t_last_tok - r.t_submit) * 1e3)
                self._spend_work(r)     # the first token
                if len(r.generated) >= r.max_new:
                    # one-token request: done at prefill, never decodes
                    # (_free_slot releases its blocks)
                    self._complete(r, t_now)
                    self._free_slot(s)
                    done_any = True
                    continue
                self._tok[s] = first
                if self._spec is not None:
                    self._spec.draft.start(
                        s, list(r.prompt) + r.generated)
        if done_any:
            self._gc_versions()

    def _fused_window_ok(self, dec):
        """The mid-window deadline clamp: deadline sweeps run only at
        window boundaries, so a window may start ONLY when the tightest
        live deadline has at least K iterations of headroom — otherwise
        this round falls back to the plain per-iteration path, which
        sweeps (and evicts) at exactly the K=1 cadence. Clamping the
        per-slot `steps` budget instead would NOT help: a scanned step
        still pays its compute when gated off, so a steps-clamped
        window's wall time is still ~K iterations — the boundary has to
        move, and the only shorter window program is the 1-wide step
        (the same ragged-tail argument behind nn/fused.py's single-step
        fallback). No rate estimate yet (cold EWMA) is treated as no
        headroom: conservative, and the plain rounds it forces are
        exactly what warms the estimate. Net pin: a tight-deadline
        request under fused_serve=K is evicted no later than at K=1
        plus one iteration of slack (the round in flight when its
        headroom first dropped below the horizon)."""
        tightest = None
        now = time.monotonic()
        for _, r in dec:
            if r.deadline is not None:
                rem = r.deadline - now
                tightest = rem if tightest is None else min(tightest,
                                                            rem)
        if tightest is None:
            return True
        if self._iter_ewma is None:
            return False
        return tightest >= self._fused * self._iter_ewma

    def _fused_iteration(self, dec, t_iter_start, n_occ):
        """One fused WINDOW: K decode iterations scanned into one
        device dispatch per live param version (`make_fused_decode_fn`
        / its paged twin), K tokens-per-slot read back in ONE transfer,
        then the host replays the window — budgets, completions,
        metrics — exactly as K plain iterations would have.

        Per-slot `steps` clamps the window to each request's remaining
        token budget (a finished slot freezes on device exactly like an
        inactive one, so neighbours' bits never see the difference);
        the paged path additionally clamps to the reservation's
        writable rows (`BlockPool.writable_rows`) and passes the bound
        as the in-program write gate `wto` — no window crosses an
        unreserved block. CoW materializes BEFORE the dispatch (the
        first scanned write lands at the frontier, inside a
        still-shared partial block — the 1-wide rule, once per window).
        Tokens past a slot's steps budget are garbage by contract and
        never consumed (`toks[:steps[s], s]` only), so nothing needs
        replaying: unconsumed scan work is discarded with the buffer.

        Observability stays PER-ITERATION: the admission estimator is
        fed K samples of (tokens at step i, window wall / K) — one
        K-sized sample would inflate its rolling median ~K-fold and
        shed feasible work — and `decode_iterations` advances by the
        window's realized iteration count while `dispatches` advances
        once per version, which is what makes `iterations_per_dispatch`
        the scraped amortization number."""
        import jax.numpy as jnp
        K = self._fused
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        if self._paged:
            self._materialize_cow(dec)
            self.metrics.record_pool(self._pool.blocks_in_use,
                                     self._pool.capacity)
        steps = np.zeros((self.slots,), np.int32)
        wto = np.zeros((self.slots,), np.int32)
        for s, r in dec:
            n = min(K, r.max_new - len(r.generated))
            if self._paged:
                # frontier row is len(prompt) + len(generated) - 1 (the
                # final emitted token is never written back — the
                # blocks_needed sizing rule); never scan past the
                # reservation
                wto[s] = self._pool.writable_rows(r.alloc)
                n = min(n, int(wto[s]) - (len(r.prompt)
                                          + len(r.generated) - 1))
            steps[s] = max(n, 0)
        versions = sorted({r.version for _, r in dec})
        win_tok = {}
        for v in versions:
            active = np.zeros((self.slots,), bool)
            for s, r in dec:
                if r.version == v:
                    active[s] = True
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                if self._paged:
                    return self._window_step(
                        aux, blocks, self._cache,
                        jnp.asarray(self._btabs), self._pos,
                        jnp.asarray(self._tok), jnp.asarray(active),
                        jnp.asarray(steps), jnp.asarray(wto))
                return self._window_step(
                    aux, blocks, self._cache, self._pos,
                    jnp.asarray(self._tok), jnp.asarray(active),
                    jnp.asarray(steps))

            # same donated-buffer retry contract as the plain step: the
            # injector site sits BEFORE the compiled call; a failure
            # inside it is terminal here (loop resets device state)
            with tr.span("decode.window", cat="serve", track="server",
                         version=v, k=K):
                if self._retry is not None:
                    toks, self._cache, self._pos = self._retry.call(
                        dispatch,
                        on_retry=lambda a, e, d: self.metrics.count(
                            "retries"))
                else:
                    toks, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            self.metrics.count("fused_windows")
            toks = np.asarray(toks)             # [K, S]
            for s, r in dec:
                if r.version == v:
                    win_tok[s] = toks[:, s]
        n_iters = int(steps.max())
        total = 0
        done_any = False
        t_now = time.monotonic()
        for s, r in dec:
            n = int(steps[s])
            if n <= 0:
                continue
            got = [int(t) for t in win_tok[s][:n]]
            r.generated.extend(got)
            self._tok[s] = got[-1]
            total += n
            self._spend_work(r, n)
            # the window lands n tokens at once: record the PER-TOKEN
            # stream rate, one sample per window per slot (the
            # speculative path's convention)
            if r.t_last_tok is not None:
                self.metrics.record_inter_token(
                    (t_now - r.t_last_tok) * 1e3 / n)
            r.t_last_tok = t_now
            if len(r.generated) >= r.max_new:
                r.generated = r.generated[:r.max_new]
                self._complete(r, t_now)
                self._free_slot(s)
                done_any = True
        self.metrics.count("tokens_out", total)
        self.metrics.count("decode_iterations", n_iters)
        if t_iter0 is not None:
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": n_occ / self.slots,
                          "accepted": total, "fused_k": K,
                          "iterations": n_iters})
        # per-window metrics fan-out: K per-iteration samples, NOT one
        # K-sized sample — see the estimator note in the docstring
        window_dt = time.monotonic() - t_iter_start
        self._note_iter_time(window_dt / K)
        for i in range(K):
            t_i = int(np.sum(steps > i))
            self._observe_rate(t_i, window_dt / K, t_i)
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _decode_iteration(self):
        """One scheduling iteration: advance PREFILLING slots one chunk
        each (chunked mode, `_chunk_iteration`), then one decode
        dispatch per live param version over the DECODING slots, active
        mask restricted to that version's slots. Plain mode advances
        every decoding slot exactly one token; speculative mode
        (`speculate=`) advances each slot 1..K tokens per dispatch
        (per-slot positions already support ragged advance)."""
        import jax.numpy as jnp
        t_iter_start = time.monotonic()
        live = [(s, r) for s, r in enumerate(self._slot_req)
                if r is not None]
        if not live:
            return False
        pf = [(s, r) for s, r in live if r.pf_next is not None]
        if pf:
            self._chunk_iteration(pf)
        # transitions/completions in the chunk pass may have changed the
        # slot map: recompute the DECODING set
        dec = [(s, r) for s, r in enumerate(self._slot_req)
               if r is not None and r.pf_next is None]
        # occupancy/live_streams recorded ONCE per scheduling iteration,
        # from the post-chunk-pass occupied count (prefilling slots
        # included, freed one-token slots excluded) — identical
        # semantics in plain and speculative modes
        n_occ = sum(1 for r in self._slot_req if r is not None)
        if n_occ:
            self.metrics.record_occupancy(n_occ, self.slots)
            self.metrics.record_live_streams(n_occ)
        if not dec:
            # pure prefill pass: zero tokens — the estimator accumulates
            # this pass's wall time into the next token-bearing sample
            # (prefill cost must dilute the measured rate, not vanish)
            self._observe_rate(0, time.monotonic() - t_iter_start, 0)
            self._after_iteration()
            return True
        if self._spec is not None:
            return self._spec_iteration(dec, t_iter_start)
        if self._fused > 1 and self._fused_window_ok(dec):
            return self._fused_iteration(dec, t_iter_start, n_occ)
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        if self._paged:
            self._materialize_cow(dec)
            self.metrics.record_pool(self._pool.blocks_in_use,
                                     self._pool.capacity)
        versions = sorted({r.version for _, r in dec})
        new_tok = {}
        for v in versions:
            active = np.zeros((self.slots,), bool)
            for s, r in dec:
                if r.version == v:
                    active[s] = True
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                if self._paged:
                    return self._step(aux, blocks, self._cache,
                                      jnp.asarray(self._btabs),
                                      self._pos,
                                      jnp.asarray(self._tok),
                                      jnp.asarray(active))
                return self._step(aux, blocks, self._cache, self._pos,
                                  jnp.asarray(self._tok),
                                  jnp.asarray(active))

            # NOTE on retry composition: cache/pos are donated, so a
            # failure INSIDE the compiled call is not retryable at this
            # level (the buffers are gone) — the injector site sits before
            # the call, which is exactly the transient class (tunnel
            # hiccup before dispatch) retries exist for.
            with tr.span("decode.dispatch", cat="serve", track="server",
                         version=v):
                if self._retry is not None:
                    nxt, _, self._cache, self._pos = self._retry.call(
                        dispatch,
                        on_retry=lambda a, e, d: self.metrics.count(
                            "retries"))
                else:
                    nxt, _, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            nxt = np.asarray(nxt)
            for s, r in dec:
                if r.version == v:
                    new_tok[s] = int(nxt[s])
        self.metrics.count("tokens_out", len(dec))
        self.metrics.count("decode_iterations")
        for s, r in dec:
            self._spend_work(r)
        done_any = False
        t_now = time.monotonic()
        for s, r in dec:
            self._tok[s] = new_tok[s]
            r.generated.append(new_tok[s])
            # one inter-token sample per decode iteration per slot
            if r.t_last_tok is not None:
                self.metrics.record_inter_token(
                    (t_now - r.t_last_tok) * 1e3)
            r.t_last_tok = t_now
            if len(r.generated) >= r.max_new:
                # the final token needs no decode step (generate() makes
                # the same point): resolve and free the slot
                r.generated = r.generated[:r.max_new]
                self._complete(r, t_now)
                self._free_slot(s)
                done_any = True
        if t_iter0 is not None:
            # one span per scheduling iteration, tagged with the two
            # numbers head-of-line surgery needs: how full the machine
            # was and how many tokens the iteration produced
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": n_occ / self.slots,
                          "accepted": len(dec)})
        dt_iter = time.monotonic() - t_iter_start
        self._note_iter_time(dt_iter)
        self._observe_rate(len(dec), dt_iter, len(dec))
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _spec_iteration(self, live, t_iter_start=None):
        """One SPECULATIVE iteration: per live version, gather each
        slot's draft (K-1 tokens, zero-padded — padding costs acceptance,
        never correctness), run ONE K-wide verify dispatch, and advance
        each slot by its accepted count (matched prefix + bonus). The
        emitted stream is the verify program's own greedy argmax chain —
        acceptance only decides the dispatch count; bit-identity with
        the plain step's stream is pinned by test (cross-width argmax
        parity, speculate.py). Draft and verify are both evaluated
        under the slot's pinned param version (`r.version`); the draft
        source itself needs no pinning because a mismatched draft cannot
        alter accepted tokens. `live` is the DECODING slot set (chunked
        mode runs prefilling slots through `_chunk_iteration` first).

        Paged mode swaps the program for the block-table verify twin
        (`make_paged_verify_fn`): the block table and a per-slot write
        bound `wto` (the reservation's row capacity —
        `BlockPool.writable_rows`) ride in as host arguments like
        tok/active, a round that crosses a block boundary writes into
        blocks the reserve-at-admit table already holds (no allocation
        here), and any pending CoW materializes FIRST — the K-wide
        write starts at the frontier, inside a still-shared partial
        block (the 1-wide CoW rule's K-wide twin)."""
        import jax.numpy as jnp
        if t_iter_start is None:
            t_iter_start = time.monotonic()
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        n_accepted = 0
        # occupancy/live_streams were recorded by _decode_iteration
        # (one record per scheduling iteration, both modes)
        K = self._spec.k
        draft = self._spec.draft
        d0 = getattr(draft, "dispatch_count", 0)   # ModelDraft device cost
        if self._paged:
            self._materialize_cow(live)
            self.metrics.record_pool(self._pool.blocks_in_use,
                                     self._pool.capacity)
        versions = sorted({r.version for _, r in live})
        done_any = False
        for v in versions:
            live_v = [(s, r) for s, r in live if r.version == v]
            active = np.zeros((self.slots,), bool)
            toks = np.zeros((self.slots, K), np.int32)
            wto = np.zeros((self.slots,), np.int32)
            n_dr = {}
            for s, r in live_v:
                active[s] = True
                if self._paged:
                    wto[s] = self._pool.writable_rows(r.alloc)
                # never request drafts past the request's remaining token
                # budget: a ModelDraft would pay real dispatches for
                # tokens that can never be accepted, and the acceptance
                # reservoir would log them as misses
                n_want = r.max_new - len(r.generated)
                dr = list(draft.propose(
                    s, min(K - 1, n_want - 1)))[:K - 1]
                n_dr[s] = len(dr)
                toks[s, :1 + len(dr)] = [r.generated[-1]] + dr
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                if self._paged:
                    return self._verify(
                        aux, blocks, self._cache,
                        jnp.asarray(self._btabs), self._pos,
                        jnp.asarray(toks), jnp.asarray(active),
                        jnp.asarray(wto))
                return self._verify(aux, blocks, self._cache, self._pos,
                                    jnp.asarray(toks), jnp.asarray(active))

            # same donated-buffer retry contract as the plain step: the
            # injector site sits BEFORE the compiled call (the transient
            # tunnel-hiccup class); a failure inside it is terminal here
            with tr.span("decode.verify", cat="serve", track="server",
                         version=v, k=K):
                if self._retry is not None:
                    nxt, n_acc, _, self._cache, self._pos = \
                        self._retry.call(
                            dispatch,
                            on_retry=lambda a, e, d: self.metrics.count(
                                "retries"))
                else:
                    nxt, n_acc, _, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            nxt = np.asarray(nxt)
            n_acc = np.asarray(n_acc)
            t_now = time.monotonic()
            for s, r in live_v:
                want = r.max_new - len(r.generated)
                take = min(int(n_acc[s]) + 1, want)
                acc = [int(t) for t in nxt[s, :take]]
                r.generated.extend(acc)
                # a speculative iteration lands `take` tokens at once:
                # record the PER-TOKEN stream rate (delta / take), one
                # sample per iteration per slot like the plain step
                if take and r.t_last_tok is not None:
                    self.metrics.record_inter_token(
                        (t_now - r.t_last_tok) * 1e3 / take)
                r.t_last_tok = t_now
                n_accepted += take
                self.metrics.count("tokens_out", take)
                self._spend_work(r, take)
                # drafted = REAL draft tokens (zero-padding is not a
                # draft); matched likewise capped — a pad that happens to
                # equal the argmax is accepted (it IS the argmax) but
                # credits luck, not the draft
                self.metrics.record_speculation(
                    take, n_dr[s], min(int(n_acc[s]), take, n_dr[s]))
                if len(r.generated) >= r.max_new:
                    self._complete(r, t_now)
                    self._free_slot(s)
                    done_any = True
                else:
                    draft.observe(s, acc)
        dd = getattr(draft, "dispatch_count", 0) - d0
        if dd:
            # a ModelDraft pays real device dispatches for its proposals;
            # count them so dispatch amortization stays honest (NGramDraft
            # never moves this — host-only)
            self.metrics.count("draft_dispatches", dd)
        if t_iter0 is not None:
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": len(live) / self.slots,
                          "accepted": n_accepted,
                          "draft_dispatches": dd})
        self.metrics.count("decode_iterations")
        self._observe_rate(n_accepted, time.monotonic() - t_iter_start,
                           len(live))
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _after_iteration(self):
        self.metrics.count("batches")       # decode iterations
        if self._reporter is not None and \
                self.metrics.count_value("batches") % self._report_every \
                == 0:
            self._reporter.report(self.metrics.snapshot())

    def _gc_versions(self):
        """Drop drained old param versions (keep indices stable: only a
        fully-drained PREFIX below the newest can be released)."""
        with self._swap_lock:
            in_use = {r.version for r in self._slot_req if r is not None}
            # a PREEMPTED request's version is pinned while it parks:
            # its artifact's rows are only resumable under exactly
            # those params (migrated-in entries carry version None and
            # bind the newest at admission)
            for r in self._resume_q:
                if r.version is not None:
                    in_use.add(r.version)
            newest = len(self._versions) - 1
            for v in range(newest):
                if v not in in_use and self._versions[v] is not None:
                    self._versions[v] = None

    def _busy(self):
        return any(r is not None for r in self._slot_req) \
            or bool(self._mem_wait) or bool(self._prio_q) \
            or bool(self._defer_q) or bool(self._resume_q) \
            or bool(self._migrate_in_q) or bool(self._migrate_cmds) \
            or bool(self._prefix_cmds) or bool(self._drain_cmds)

    def _loop_once(self):
        if self._killed:
            # crash-injection verb (kill()): fail everything loudly and
            # let the loop exit — no drain, no persistence
            self._die_now()
            return
        self._service_drain()
        if self._paged:
            # drain the client-side migrate-in staging into the serve-
            # thread-only resume line, then answer export commands —
            # both BEFORE the deadline sweep so a just-arrived artifact
            # is swept/served this iteration
            while self._migrate_in_q:
                self._resume_q.append(self._migrate_in_q.popleft())
            self._service_migrations()
            self._service_prefix_ops()
        # evict deadline-expired slots FIRST so the admit below can refill
        # them in the same iteration
        self._evict_expired()
        # idle (no slot occupied): block on the queue up to 50 ms instead
        # of spinning at the decode tick; busy: drain the queue non-blocking
        self._admit_pending(timeout=0.0 if self._busy() else 0.05)
        try:
            busy = self._decode_iteration()
        except BaseException as e:  # noqa: BLE001 — fail slots, survive
            # a decode dispatch failed terminally (non-retryable, or
            # retries exhausted). The donated cache/pos buffers cannot be
            # trusted after a failed call, so every occupied request
            # fails LOUDLY and the slot state resets — the server keeps
            # serving instead of stranding all future requests on a dead
            # thread.
            n_failed = 0
            for r in self._slot_req:
                if r is not None and _fail_future(r.future, e):
                    n_failed += 1
            if n_failed:
                self.metrics.count("failed", n_failed)
            self._reset_device_state()
            self._gc_versions()
            return
        if not busy:
            # idle: still GC param versions (repeated swaps on an idle
            # server must not accumulate dead params); the next loop's
            # blocking admit is the idle wait, no sleep needed
            self._gc_versions()
